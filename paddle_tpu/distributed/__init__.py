"""paddle_tpu.distributed (reference surface: python/paddle/distributed/).

Bootstrapping maps to jax.distributed (the TCPStore analogue,
SURVEY.md N23); groups map to mesh axes; collectives map to lax primitives
over ICI/DCN (N19/N22/N24 → §5.8).
"""
from __future__ import annotations

import os

import jax

from . import collective, mesh
from .collective import (ReduceOp, all_gather, all_gather_object, all_reduce,
                         all_to_all, all_to_all_single, alltoall, barrier,
                         broadcast, get_group, irecv, isend, new_group, recv,
                         reduce, reduce_scatter, scatter, send, wait)
from .mesh import (CommunicateTopology, HybridCommunicateGroup, get_mesh,
                   init_hybrid_mesh, init_mesh, named_sharding, set_mesh)
from .parallel_base import (DataParallel, ParallelEnv, get_rank,
                            get_world_size, init_parallel_env, parallelize,
                            shard_tensor, shard_dataloader)
from . import auto_parallel
from . import fleet
from .sharding import group_sharded_parallel, save_group_sharded_model
from . import moe, mp_layers, pipeline, ring_attention
from .recompute import recompute, recompute_sequential

__all__ = [
    "ReduceOp", "all_reduce", "all_gather", "reduce_scatter", "broadcast",
    "reduce", "scatter", "alltoall", "all_to_all", "send", "recv", "barrier",
    "new_group", "get_group", "init_parallel_env", "get_rank",
    "get_world_size", "ParallelEnv", "DataParallel", "init_mesh", "get_mesh",
    "parallelize", "shard_tensor", "fleet", "spawn", "launch",
]


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """reference: python/paddle/distributed/spawn.py.

    On TPU the single-controller model replaces per-GPU process spawn: the
    function runs once and pjit/shard_map fans work across devices.  For
    API compatibility we run func(rank=0) inline when nprocs<=1 and use
    multiprocessing otherwise (CPU testing only).
    """
    if nprocs in (-1, 0, 1):
        return func(*args)
    import multiprocessing as mp
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        env = {"PADDLE_TRAINER_ID": str(rank),
               "PADDLE_TRAINERS_NUM": str(nprocs)}
        p = ctx.Process(target=_spawn_entry, args=(func, args, env),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
    return procs


def _spawn_entry(func, args, env):
    os.environ.update(env)
    func(*args)


def __getattr__(name):
    # lazy: `paddle.distributed.launch` is the launcher module (reference:
    # python/paddle/distributed/launch).  Imported on attribute access so
    # `python -m paddle_tpu.distributed.launch_main` doesn't trigger the
    # runpy double-import warning.
    if name == "launch":
        from . import launch_main
        return launch_main
    raise AttributeError(name)

from . import utils  # noqa: E402,F401
