"""Activation recompute (reference: python/paddle/distributed/fleet/utils/
recompute.py:199,:331 — a PyLayer that re-runs the block in backward under
RNGStatesTracker).

TPU-native: ``jax.checkpoint`` (remat) IS recompute, applied at a functional
boundary.  ``recompute(fn, *args)`` works on both paths:

* compiled path (inside jit/grad trace): wraps the block in jax.checkpoint
  so XLA rematerialises its activations in backward — identical memory/
  compute trade as the reference, chosen by the same call-site annotation.
* eager tape path: records ONE GradNode for the whole block whose vjp
  re-runs the block under jax.vjp at backward time — activations inside the
  block are not held by the tape (the PyLayer behavior).
"""
from __future__ import annotations

import jax

from ..core import random as _rnd
from ..core.dispatch import call, unwrap
from ..core.grad_mode import no_grad
from ..core.tensor import Tensor


def recompute(function, *args, use_reentrant=True, preserve_rng_state=True,
              **kwargs):
    """Run ``function(*args)`` with activation rematerialisation.

    ``function`` may be a Layer or any callable over Tensors.
    """
    key = _rnd.next_key() if preserve_rng_state else None
    tensor_args = [a if isinstance(a, Tensor) else Tensor(a) for a in args]
    # the block's parameters must be explicit vjp inputs, or the eager tape
    # would treat them as constants and drop their gradients
    params = (list(function.parameters())
              if hasattr(function, "parameters") else [])
    n_in = len(tensor_args)

    def raw(*arrays):
        def inner(*arrs):
            ins, p_arrs = arrs[:n_in], arrs[n_in:]
            old = [p._array for p in params]
            for p, a in zip(params, p_arrs):
                p._array = a
            try:
                ctx = _rnd.key_stream(key) if key is not None else _nullctx()
                with no_grad(), ctx:
                    out = function(*[Tensor(a) for a in ins], **kwargs)
                return unwrap(out)
            finally:
                for p, a in zip(params, old):
                    p._array = a
        return jax.checkpoint(inner)(*arrays)

    return call(raw, *tensor_args, *params, name="recompute")


class _nullctx:
    def __enter__(self):
        return None

    def __exit__(self, *a):
        return False


def recompute_sequential(functions, x, segments=1):
    """Checkpoint a Sequential in ``segments`` chunks
    (reference: recompute_sequential in later paddle; here for parity)."""
    layers = list(functions)
    n = len(layers)
    per = max(n // max(segments, 1), 1)
    i = 0
    while i < n:
        chunk = layers[i:i + per]

        def run_chunk(inp, _chunk=chunk):
            for l in _chunk:
                inp = l(inp)
            return inp

        run_chunk.parameters = lambda _chunk=chunk: [
            p for l in _chunk if hasattr(l, "parameters")
            for p in l.parameters()]
        x = recompute(run_chunk, x)
        i += per
    return x
