"""Mesh management — the TPU-native replacement for the reference's NCCL
ring registry (paddle/fluid/platform/collective_helper.h:71 NCCLCommContext:
ring_id -> comm) and fleet topology
(fleet/base/topology.py:52 CommunicateTopology / :133 HybridCommunicateGroup).

A named `jax.sharding.Mesh` axis plays the role of a comm ring; the global
mesh (set once per process) plays the role of the ring registry.  Axis order
follows the reference's fixed hybrid order ["data", "pipe", "sharding",
"sep", "model", "expert"] projected onto the axes actually requested.
"""
from __future__ import annotations

import contextlib
from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# canonical axis order (outer..inner). DCN-crossing axes (dp/pp) outermost so
# tensor-parallel collectives ride ICI — SURVEY.md §5.8.
AXIS_ORDER = ("dp", "pp", "sdp", "sep", "mp", "ep")

_global_mesh: Optional[Mesh] = None


def init_mesh(axes: Dict[str, int], devices=None) -> Mesh:
    """Create + install the global mesh.  axes e.g. {"dp": 2, "mp": 4}."""
    global _global_mesh
    devices = devices if devices is not None else jax.devices()
    names = [a for a in AXIS_ORDER if a in axes]
    extra = [a for a in axes if a not in AXIS_ORDER]
    names += extra
    sizes = [axes[a] for a in names]
    n = int(np.prod(sizes)) if sizes else 1
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    dev_array = np.asarray(devices[:n]).reshape(sizes if sizes else (1,))
    _global_mesh = Mesh(dev_array, tuple(names) if names else ("dp",))
    return _global_mesh


def init_hybrid_mesh(dcn_axes: Dict[str, int], ici_axes: Dict[str, int],
                     devices=None) -> Mesh:
    """Multi-slice mesh: ``dcn_axes`` span slices over the data-center
    network, ``ici_axes`` stay within a slice's ICI fabric.

    The analogue of the reference's FleetExecutor cross-cluster pipelining
    (fleet_executor/, SURVEY.md N25) and ProcessGroupHeter's
    intra-NCCL/inter-RPC split (ProcessGroupHeter.h:64): communication-heavy
    axes (tensor/sequence/expert parallel) are laid out on ICI; only the
    bandwidth-light axes (data/pipeline) cross DCN.  Built with
    jax.experimental.mesh_utils.create_hybrid_device_mesh so the device
    order matches the physical slice topology; falls back to a plain mesh
    when all devices are one slice (CPU tests, single slice)."""
    global _global_mesh
    devices = devices if devices is not None else jax.devices()
    dcn_names = [a for a in AXIS_ORDER if a in dcn_axes] + \
        [a for a in dcn_axes if a not in AXIS_ORDER]
    ici_names = [a for a in AXIS_ORDER if a in ici_axes] + \
        [a for a in ici_axes if a not in AXIS_ORDER]
    overlap = set(dcn_names) & set(ici_names)
    if overlap:
        raise ValueError(f"axes cannot be both DCN and ICI: {sorted(overlap)}")
    dcn_shape = [dcn_axes[a] for a in dcn_names]
    ici_shape = [ici_axes[a] for a in ici_names]
    names = tuple(dcn_names + ici_names)
    sizes = dcn_shape + ici_shape
    # mesh_utils needs per-device slice topology (slice_index); CPU/mock
    # devices don't have it — those take the row-major fallback below
    has_slices = all(getattr(d, "slice_index", None) is not None
                     for d in devices)
    if has_slices:
        from jax.experimental import mesh_utils
        # contract: mesh_shape and dcn_mesh_shape must be the SAME rank;
        # pad each side with 1s so the result's shape is dcn_shape+ici_shape
        dev_array = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=[1] * len(dcn_shape) + ici_shape,
            dcn_mesh_shape=dcn_shape + [1] * len(ici_shape),
            devices=devices)
        _global_mesh = Mesh(dev_array.reshape(sizes), names)
    else:
        # single-slice / CPU-mesh fallback: row-major assignment with the
        # DCN axes outermost (they change slowest -> contiguous slices)
        n = int(np.prod(sizes)) if sizes else 1
        if n > len(devices):
            raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
        _global_mesh = Mesh(np.asarray(devices[:n]).reshape(sizes), names)
    return _global_mesh


def get_mesh() -> Optional[Mesh]:
    return _global_mesh


@contextlib.contextmanager
def mesh_scope(mesh: Mesh):
    """Temporarily install ``mesh`` as the global mesh (restored on exit).

    The serving engine's tensor-parallel entries trace under this scope so
    the model's ``with_sharding_constraint`` sites resolve the SERVING
    mesh (a private ``('mp',)`` mesh over the TP devices) instead of
    whatever training mesh the process may or may not have installed —
    without the engine ever mutating global state beyond its own traced
    calls."""
    global _global_mesh
    prev = _global_mesh
    _global_mesh = mesh
    try:
        yield mesh
    finally:
        _global_mesh = prev


def set_mesh(mesh: Mesh):
    global _global_mesh
    _global_mesh = mesh


def ensure_mesh() -> Mesh:
    global _global_mesh
    if _global_mesh is None:
        init_mesh({"dp": len(jax.devices())})
    return _global_mesh


def axis_size(name: str) -> int:
    mesh = get_mesh()
    if mesh is None or name not in mesh.axis_names:
        return 1
    return mesh.shape[name]


def named_sharding(*spec) -> NamedSharding:
    return NamedSharding(ensure_mesh(), PartitionSpec(*spec))


class CommunicateTopology:
    """reference parity: fleet/base/topology.py:52 — cartesian rank topology
    over named axes."""

    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "model"),
                 dims=(1, 1, 1, 1)):
        self._names = list(hybrid_group_names)
        self._dims = list(dims)
        self._world = int(np.prod(dims))
        self._coords = list(np.ndindex(*dims))
        self._coord_to_rank = {c: i for i, c in enumerate(self._coords)}

    def get_hybrid_group_names(self):
        return self._names

    def get_dim(self, name):
        return self._dims[self._names.index(name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world

    def get_rank(self, **kwargs):
        coord = tuple(kwargs[n] for n in self._names)
        return self._coord_to_rank[coord]

    def get_coord(self, rank):
        return self._coords[rank]

    def get_axis_list(self, axis_name, index):
        axis = self._names.index(axis_name)
        return [r for r, c in enumerate(self._coords) if c[axis] == index]

    def get_comm_list(self, axis_name):
        """All groups along axis_name (reference: topology.py get_comm_list)."""
        axis = self._names.index(axis_name)
        other_dims = [d for i, d in enumerate(self._dims) if i != axis]
        groups = []
        for other in np.ndindex(*other_dims):
            group = []
            for k in range(self._dims[axis]):
                coord = list(other)
                coord.insert(axis, k)
                group.append(self._coord_to_rank[tuple(coord)])
            groups.append(group)
        return groups

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = list(self.get_coord(global_rank))
        for name, idx in kwargs.items():
            coord[self._names.index(name)] = idx
        return self._coord_to_rank[tuple(coord)]


class HybridCommunicateGroup:
    """reference parity: fleet/base/topology.py:133.

    On TPU every "communication group" is a mesh axis name; this object maps
    the fleet nomenclature (dp/pp/sharding/mp groups, ranks within each) onto
    the global mesh and a virtual rank (process_index-major).
    """

    AXIS_MAP = {"data": "dp", "pipe": "pp", "sharding": "sdp", "model": "mp",
                "sep": "sep", "expert": "ep"}

    def __init__(self, topology: CommunicateTopology, global_rank: int = 0):
        self._topo = topology
        self.global_rank = global_rank
        self.nranks = topology.world_size()
        for name in topology.get_hybrid_group_names():
            setattr(self, f"_{name}_degree", topology.get_dim(name))

    # data parallel
    def get_data_parallel_rank(self):
        return self._topo.get_coord(self.global_rank)[
            self._topo._names.index("data")]

    def get_data_parallel_world_size(self):
        return self._topo.get_dim("data")

    def get_data_parallel_group(self):
        return _AxisGroup("dp", self._topo, "data", self.global_rank)

    def get_data_parallel_group_src_rank(self):
        return self._topo.get_axis_list(
            "data", 0)[0] if self.nranks else 0

    # model (tensor) parallel
    def get_model_parallel_rank(self):
        return self._topo.get_coord(self.global_rank)[
            self._topo._names.index("model")]

    def get_model_parallel_world_size(self):
        return self._topo.get_dim("model")

    def get_model_parallel_group(self):
        return _AxisGroup("mp", self._topo, "model", self.global_rank)

    def get_model_parallel_group_src_rank(self):
        return 0

    # pipeline
    def get_stage_id(self):
        return self._topo.get_coord(self.global_rank)[
            self._topo._names.index("pipe")]

    def get_pipe_parallel_world_size(self):
        return self._topo.get_dim("pipe")

    def get_pipe_parallel_group(self):
        return _AxisGroup("pp", self._topo, "pipe", self.global_rank)

    # sharding
    def get_sharding_parallel_rank(self):
        return self._topo.get_coord(self.global_rank)[
            self._topo._names.index("sharding")]

    def get_sharding_parallel_world_size(self):
        return self._topo.get_dim("sharding")

    def get_sharding_parallel_group(self):
        return _AxisGroup("sdp", self._topo, "sharding", self.global_rank)

    def get_parallel_mode(self):
        if self.get_model_parallel_world_size() > 1 or \
                self.get_pipe_parallel_world_size() > 1:
            return "hybrid"
        if self.get_sharding_parallel_world_size() > 1:
            return "sharding"
        return "data"

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank


class _AxisGroup:
    """A communication group = one mesh axis (ring_id analogue)."""

    def __init__(self, axis, topo, topo_name, global_rank):
        self.axis = axis
        self._topo = topo
        self._name = topo_name
        self._global_rank = global_rank
        self.nranks = topo.get_dim(topo_name)
        coord = topo.get_coord(global_rank)
        self.rank = coord[topo._names.index(topo_name)]

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, global_rank):
        coord = self._topo.get_coord(global_rank)
        return coord[self._topo._names.index(self._name)]

    @property
    def ranks(self):
        idx = [i for i, n in enumerate(self._topo._names) if n != self._name]
        my = self._topo.get_coord(self._global_rank)
        return [r for r, c in enumerate(self._topo._coords)
                if all(c[i] == my[i] for i in idx)]
