"""Device management (reference: python/paddle/device/ — set_device, cuda
streams API).  TPU-native: devices come from jax; streams/events are no-ops
because XLA owns scheduling (reference needed explicit CUDA streams,
paddle/fluid/platform/device_context.h)."""
from __future__ import annotations

import jax

_current = [None]


def get_all_devices():
    return jax.devices()


def set_device(device: str):
    _current[0] = device
    return device


def get_device() -> str:
    if _current[0] is not None:
        return _current[0]
    d = jax.devices()[0]
    return f"{d.platform}:{d.id}"


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


def device_count():
    return jax.device_count()


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def is_compiled_with_ipu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


class Stream:
    """API-compat stub: XLA schedules asynchronously; explicit streams are not
    a TPU concept."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()

    def wait_event(self, event):
        pass


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize()


def synchronize(device=None):
    """Block until all queued work completes (paddle.device.synchronize)."""
    for d in jax.live_arrays():
        pass
    (jax.device_put(0) + 0).block_until_ready()


def current_stream(device=None):
    return Stream(device)


def stream_guard(stream):
    import contextlib
    return contextlib.nullcontext()


class cuda:
    """paddle.device.cuda compat namespace (maps to the accelerator)."""
    Stream = Stream
    Event = Event

    @staticmethod
    def device_count():
        return jax.device_count()

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def max_memory_allocated(device=None):
        try:
            stats = jax.devices()[0].memory_stats()
            return stats.get("peak_bytes_in_use", 0)
        except Exception:
            return 0

    @staticmethod
    def memory_allocated(device=None):
        try:
            stats = jax.devices()[0].memory_stats()
            return stats.get("bytes_in_use", 0)
        except Exception:
            return 0

    @staticmethod
    def max_memory_reserved(device=None):
        return cuda.max_memory_allocated(device)

    @staticmethod
    def memory_reserved(device=None):
        return cuda.memory_allocated(device)

    @staticmethod
    def empty_cache():
        pass
