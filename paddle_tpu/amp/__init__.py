"""Automatic mixed precision (reference surface: python/paddle/amp/ —
auto_cast O1/O2 lists at auto_cast.py:21, GradScaler at grad_scaler.py:26).

TPU-native policy: bf16 is the default mixed dtype and needs NO loss scaling
(full fp32 exponent range), so ``GradScaler`` with bf16 is an API-compatible
pass-through; dynamic loss scaling is implemented for explicit fp16 use.

Mechanism: ``auto_cast`` installs a global amp state consulted by the op
dispatcher — white-listed ops (matmul/conv: the MXU ops) cast fp32 inputs to
the amp dtype; black-listed ops stay fp32.  Under O2, ``decorate`` casts the
model's parameters themselves.
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..robustness.faultpoints import declare as _declare, faultpoint

_declare("amp.found_inf",
         "override the GradScaler's found-inf verdict (ForceFoundInf "
         "simulates an fp16 overflow step without overflow-scale grads)")

# Reference O1 lists (auto_cast.py): ops that are numerically safe + MXU-bound
WHITE_LIST = {"matmul", "bmm", "mm", "conv1d", "conv2d", "conv3d", "linear",
              "einsum", "mv", "addmm"}
BLACK_LIST = {"exp", "log", "log2", "log10", "log1p", "pow", "square",
              "softmax_with_cross_entropy", "cross_entropy", "cumsum",
              "logsumexp", "norm", "mean", "sum", "var", "std",
              "layer_norm", "batch_norm", "rsqrt", "softmax"}

_amp_state = {"enable": False, "dtype": np.dtype("float32"), "level": "O1",
              "white": WHITE_LIST, "black": BLACK_LIST}


def amp_state():
    return _amp_state


def amp_cast_inputs(op_name, arrays):
    """Called by the dispatcher: cast fp32 inputs of white-listed ops."""
    st = _amp_state
    if not st["enable"]:
        return arrays
    if op_name in st["black"]:
        return arrays
    level = st["level"]
    if level == "O2" or op_name in st["white"]:
        dt = st["dtype"]
        out = []
        for a in arrays:
            if hasattr(a, "dtype") and a.dtype == jnp.float32:
                out.append(a.astype(dt))
            else:
                out.append(a)
        return out
    return arrays


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    """reference parity: paddle.amp.auto_cast (auto_cast.py:21)."""
    from ..core.dtype import convert_dtype
    prev = dict(_amp_state)
    _amp_state["enable"] = enable
    _amp_state["dtype"] = convert_dtype(dtype)
    _amp_state["level"] = level
    if custom_white_list:
        _amp_state["white"] = WHITE_LIST | set(custom_white_list)
    if custom_black_list:
        _amp_state["black"] = BLACK_LIST | set(custom_black_list)
    try:
        yield
    finally:
        _amp_state.update(prev)


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """reference parity: paddle.amp.decorate (auto_cast.py:81) — O2 casts
    parameters to the amp dtype (master fp32 weights are kept by optimizers
    whose slots are fp32, which ours are)."""
    from ..nn.layer.norm import _BatchNormBase, LayerNorm

    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            for layer in m.sublayers(include_self=True):
                if isinstance(layer, (_BatchNormBase, LayerNorm)):
                    continue  # keep norms fp32 (reference keep_batch_norm_fp32)
                # layers holding norm params inline (e.g. GPTScanBlocks'
                # stacked LN arrays) declare them by name
                keep = getattr(layer, "_amp_keep_fp32_params", ())
                for name, p in layer._parameters.items():
                    if name in keep:
                        continue
                    if p is not None and p.dtype == np.dtype("float32"):
                        p._array = p._array.astype(dtype)
    if optimizers is None:
        return models if single else model_list
    return (models if single else model_list), optimizers


class GradScaler:
    """Dynamic loss scaling (reference: grad_scaler.py:26 over
    fluid/dygraph/amp/loss_scaler.py:40 AmpScaler).

    With bf16 (TPU default) scaling is unnecessary — ``enable=False`` makes
    every method a pass-through, and that is the recommended mode.
    """

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._last_skipped = False
        self._already_unscaled = set()

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        if id(optimizer) in self._already_unscaled:
            return  # never divide by the scale twice (explicit + step())
        self._already_unscaled.add(id(optimizer))
        inv = 1.0 / self._scale
        found_inf = False
        for p in optimizer._parameter_list:
            if p.grad is not None:
                arr = p.grad._array * inv
                finite = bool(jnp.all(jnp.isfinite(arr)))
                if not finite:
                    found_inf = True
                p.grad._array = arr
        self._found_inf = found_inf

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            self._last_skipped = False
            return
        self.unscale_(optimizer)   # no-op if the user already unscaled
        ctx = faultpoint("amp.found_inf", found_inf=self._found_inf)
        if ctx is not None:
            self._found_inf = bool(ctx["found_inf"])
        # recorded BEFORE _update resets the flag: DivergenceSentinel reads
        # this to tell "the fp16 gate already skipped the poisoned update"
        # (params intact — no rewind needed) from a real divergence
        self._last_skipped = self._found_inf
        if self._found_inf:
            from ..observability import registry as _metrics
            _metrics.counter("train.amp_skipped_steps").inc()
        else:
            optimizer.step()
        self._already_unscaled.discard(id(optimizer))
        self._update()

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)

    def update(self):
        pass  # folded into step() as in the reference eager path

    def _update(self):
        if not self._dynamic:
            self._found_inf = False
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    @property
    def last_step_skipped(self) -> bool:
        """True iff the most recent ``step()`` skipped the optimizer update
        because non-finite gradients were found (the fp16 overflow path)."""
        return self._last_skipped

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {"scale": self._scale, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps, "enable": self._enable}

    def load_state_dict(self, sd):
        self._scale = sd["scale"]
        self._good_steps = sd["good_steps"]
        self._bad_steps = sd["bad_steps"]

    set_state_dict = load_state_dict
