"""Pallas TPU fused softmax-cross-entropy (hard labels).

The reference fuses softmax+CE in one CUDA kernel
(paddle/phi/kernels/gpu/cross_entropy_kernel.cu); the XLA path here is two
streaming reductions (max, sum-exp) plus a gather over the (N, V) logits —
measured ~12 ms/step on the GPT-2 345M bench (V = 50304).  This kernel
computes the row statistics, the label gather AND the loss in one pass over
a VMEM-resident row tile, and the backward writes dlogits directly from the
saved (m, lse) statistics:

    nll_i  = lse_i - logits[i, y_i]
    dlogits[i, v] = (exp(logits[i, v] - lse_i) - 1[v == y_i]) * g_i

Gather-free: the label column is extracted with an iota==label masked sum
(a VPU pass over the resident tile, no scalar loads).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..core.dtype import x64_scope
from jax.experimental.pallas import tpu as pltpu  # noqa: F401
from .pallas_compat import CompilerParams

DEFAULT_BLOCK_ROWS = 8


def supported(n_rows: int, vocab: int, block_rows: int = DEFAULT_BLOCK_ROWS):
    """Tileability + VMEM budget for the resident (R, V) tile: the bf16
    tile is double-buffered and the kernel's f32 elementwise chain
    materialises ~3 tile-sized temporaries in VMEM."""
    if n_rows <= 0 or vocab % 128 or n_rows % 8:
        return False
    br = _row_block(n_rows)
    if n_rows % br:
        return False
    return br * vocab * (2 * 2 + 4 * 3) <= 10 * 1024 * 1024


def _fwd_kernel(x_ref, y_ref, nll_ref, lse_ref):
    x = x_ref[...].astype(jnp.float32)                   # (R, V)
    y = y_ref[...][:, 0]                                 # (R,) i32
    m = jnp.max(x, axis=-1)
    e = jnp.exp(x - m[:, None])
    lse = m + jnp.log(jnp.sum(e, axis=-1))
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    t = jnp.sum(jnp.where(cols == y[:, None], x, jnp.float32(0.0)), axis=-1)
    nll_ref[...] = (lse - t)[:, None]
    lse_ref[...] = lse[:, None]


def _bwd_kernel(x_ref, y_ref, lse_ref, g_ref, dx_ref):
    x = x_ref[...].astype(jnp.float32)                   # (R, V)
    y = y_ref[...][:, 0]
    lse = lse_ref[...][:, 0]
    g = g_ref[...][:, 0]
    p = jnp.exp(x - lse[:, None])
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    onehot = (cols == y[:, None]).astype(jnp.float32)
    dx_ref[...] = ((p - onehot) * g[:, None]).astype(dx_ref.dtype)


def _row_block(n):
    # DEFAULT_BLOCK_ROWS is the VMEM-bound maximum; with the n % 8 == 0
    # gate this is currently always 8, but keep the shrink for future
    # larger defaults
    br = min(DEFAULT_BLOCK_ROWS, max(n, 1))
    while br > 8 and n % br:
        br //= 2
    return br


def _ce_fwd(x2, y2, interpret):
    n, v = x2.shape
    br = _row_block(n)
    row = pl.BlockSpec((br, 1), lambda i: (i, 0))
    nll, lse = pl.pallas_call(
        _fwd_kernel,
        grid=(n // br,),
        in_specs=[pl.BlockSpec((br, v), lambda i: (i, 0)), row],
        out_specs=[row, row],
        out_shape=[jax.ShapeDtypeStruct((n, 1), jnp.float32)] * 2,
        interpret=interpret,
    )(x2, y2)
    return nll, lse


def _ce_bwd(x2, y2, lse, g, interpret):
    n, v = x2.shape
    br = _row_block(n)
    row = pl.BlockSpec((br, 1), lambda i: (i, 0))
    return pl.pallas_call(
        _bwd_kernel,
        grid=(n // br,),
        in_specs=[pl.BlockSpec((br, v), lambda i: (i, 0)), row, row, row],
        out_specs=pl.BlockSpec((br, v), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, v), x2.dtype),
        interpret=interpret,
    )(x2, y2, lse, g)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def softmax_ce_pallas(logits2, labels2, interpret=False):
    """logits2: (N, V); labels2: (N, 1) int32 (pre-clipped to [0, V)).
    Returns per-row nll (N,) f32."""
    with x64_scope(False):
        nll, _ = _ce_fwd(logits2, labels2, interpret)
    return nll[:, 0]


def _vjp_fwd(logits2, labels2, interpret):
    with x64_scope(False):
        nll, lse = _ce_fwd(logits2, labels2, interpret)
    return nll[:, 0], (logits2, labels2, lse)


def _vjp_bwd(interpret, res, g):
    logits2, labels2, lse = res
    with x64_scope(False):
        dx = _ce_bwd(logits2, labels2, lse,
                     g.astype(jnp.float32)[:, None], interpret)
    return dx, None


softmax_ce_pallas.defvjp(_vjp_fwd, _vjp_bwd)


# ---------------------------------------------------------------------------
# streamed one-pass LSE (v2)
# ---------------------------------------------------------------------------
# The resident-row kernel above is VMEM-capped at 8-row tiles, whose grid
# overhead loses to XLA (PERF.md round-3 log).  This kernel instead streams
# the vocab axis through a 2-D grid (row blocks x vocab chunks) with
# flash-attention-style online (max, sum-exp2) statistics in scratch — big
# tiles, ONE pass over the bf16 logits where the XLA path runs two
# streaming reductions (measured ~12 ms/step at GPT-2 345M shapes).  The
# label gather stays outside (XLA's take_along_axis reads only N elements).
# Base-2 like the flash kernels: exp lowers to native exp2.

_LOG2E = 1.4426950408889634


def _lse_chunk(v: int, br: int, itemsize: int) -> int:
    # largest lane-aligned divisor of v whose input tile (double-buffered
    # at the logits' own itemsize) plus the kernel's ~2 f32 tile
    # temporaries fits the VMEM budget
    budget = 10 * 1024 * 1024
    best = 0
    for c in range(128, v + 1, 128):
        if v % c == 0 and br * c * (2 * itemsize + 4 * 2) <= budget:
            best = c
    return best


def _lse_layout(n: int, v: int, itemsize: int = 2):
    """Joint (row_block, chunk) pick: a GPT vocab like 50304 = 393*128 has
    only coarse lane-aligned divisors (384 vs 16768), so a big row block
    can force a uselessly small chunk — prefer the largest row block whose
    admissible chunk is still >= 1024 lanes."""
    for br in (256, 128, 64, 32, 16, 8):
        if n % br:
            continue
        c = _lse_chunk(v, br, itemsize)
        if c >= 1024:
            return br, c
    return 0, 0


def lse_supported(n_rows: int, vocab: int, itemsize: int = 2) -> bool:
    if n_rows <= 0 or vocab % 128:
        return False
    return _lse_layout(n_rows, vocab, itemsize)[0] > 0


def _valid_lse_cfg(n, v, rb, cc) -> bool:
    """Shared (row_block, chunk) validity predicate: used by BOTH the
    candidate generator and _lse_call's dispatch validator so a tuned
    winner can never pass one and silently fail the other."""
    return (isinstance(rb, int) and isinstance(cc, int) and rb > 0
            and cc >= 128 and cc % 128 == 0 and n % rb == 0
            and v % cc == 0)


def _lse_kernel(x_ref, lse_ref, m_sc, l_sc, *, nv):
    vi = jax.lax.convert_element_type(pl.program_id(1), jnp.int32)

    @pl.when(vi == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, -1e30)
        l_sc[...] = jnp.zeros_like(l_sc)

    # base-2 scaled logits: one fused convert+mul pass over the tile
    xs = x_ref[...].astype(jnp.float32) * jnp.float32(_LOG2E)   # (BR, C)
    m_old = m_sc[...]
    m_new = jnp.maximum(m_old, jnp.max(xs, axis=-1))
    l_new = l_sc[...] * jnp.exp2(m_old - m_new) + \
        jnp.sum(jnp.exp2(xs - m_new[:, None]), axis=-1)
    m_sc[...] = m_new
    l_sc[...] = l_new

    @pl.when(vi == nv - 1)
    def _emit():
        # lse in base-e units (what the CE criterion consumes)
        lse_ref[...] = ((m_new + jnp.log2(jnp.maximum(l_new, 1e-30)))
                        / jnp.float32(_LOG2E))[:, None]


def _lse_call_cfg(x2, br, c, interpret):
    n, v = x2.shape
    nv = v // c
    return pl.pallas_call(
        functools.partial(_lse_kernel, nv=nv),
        grid=(n // br, nv),
        in_specs=[pl.BlockSpec((br, c), lambda r, k: (r, k))],
        out_specs=pl.BlockSpec((br, 1), lambda r, k: (r, 0)),
        out_shape=jax.ShapeDtypeStruct((n, 1), jnp.float32),
        scratch_shapes=[pltpu.VMEM((br,), jnp.float32),
                        pltpu.VMEM((br,), jnp.float32)],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(x2)


def autotune_key(n, v, dtype):
    from . import autotune as at
    return {"n": int(n), "v": int(v), "dtype": str(jnp.dtype(dtype)),
            "platform": at.platform()}


def _lse_candidates(key):
    """ce_lse autotune family: (row_block, vocab_chunk) tile layouts.
    Candidate [0] is exactly what _lse_layout hand-picks today; the rest
    are every admissible row block with its largest chunk plus a
    half-sized chunk (more grid steps, smaller working set)."""
    n, v = key["n"], key["v"]
    itemsize = jnp.dtype(key["dtype"]).itemsize
    br0, c0 = _lse_layout(n, v, itemsize)
    cands = []
    if br0:
        cands.append({"variant": "base",
                      "config": {"block_rows": br0, "chunk": c0}})
    for br in (256, 128, 64, 32, 16, 8):
        if n % br:
            continue
        c = _lse_chunk(v, br, itemsize)
        if not c:
            continue
        for cc in (c, c // 2):
            if _valid_lse_cfg(n, v, br, cc):
                cand = {"variant": "base",
                        "config": {"block_rows": br, "chunk": cc}}
                if cand not in cands:
                    cands.append(cand)
    return cands


#: per-key synthetic logits shared across the candidates of one tune()
#: run (the bench key is ~1.6 GB — regenerating + re-transferring it per
#: candidate would dominate warm time); freed by the cleanup hook
_LSE_RUNNER_DATA: dict = {}


def _lse_runner(cand, key):
    import numpy as np
    from . import autotune as at
    cfg = cand["config"]
    n, v = key["n"], key["v"]
    interpret = key["platform"] != "tpu"
    ks = at.key_str(key)
    x2 = _LSE_RUNNER_DATA.get(ks)
    if x2 is None:
        x2 = jnp.asarray(
            np.random.RandomState(0).standard_normal((n, v)),
            jnp.dtype(key["dtype"]))
        _LSE_RUNNER_DATA[ks] = x2

    def timed(x):
        # same x64-off trace scope as the production entry
        # (logsumexp_pallas) — see flash_attention_pallas._bwd_runner
        with x64_scope(False):
            return _lse_call_cfg(x, cfg["block_rows"], cfg["chunk"],
                                 interpret)
    fn = jax.jit(timed)

    def run():
        jax.block_until_ready(fn(x2))
    return run


def _lse_runner_cleanup(key):
    from . import autotune as at
    _LSE_RUNNER_DATA.pop(at.key_str(key), None)


def _lse_traceable(cand, key):
    """Data-free candidate program for the TPU504 VMEM estimator and the
    trace-tier audit (see flash_attention_pallas._fwd_traceable)."""
    n, v = key["n"], key["v"]
    cfg = cand["config"]

    def fn(x):
        with x64_scope(False):
            return _lse_call_cfg(x, cfg["block_rows"], cfg["chunk"], True)
    return fn, (jax.ShapeDtypeStruct((n, v), jnp.dtype(key["dtype"])),)


def _lse_register():
    from . import autotune as at
    at.register_family("ce_lse", _lse_candidates, _lse_runner,
                       cleanup=_lse_runner_cleanup,
                       traceable=_lse_traceable)


def _lse_call(x2, interpret):
    n, v = x2.shape
    br, c = _lse_layout(n, v, x2.dtype.itemsize)
    from . import autotune as at
    cand = at.resolve("ce_lse", autotune_key(n, v, x2.dtype))
    cfg = cand.get("config", {})
    rb, cc = cfg.get("block_rows"), cfg.get("chunk")
    if _valid_lse_cfg(n, v, rb, cc):
        br, c = rb, cc      # tuned/pinned layout (validated; bad cache
    return _lse_call_cfg(x2, br, c, interpret)  # entries fall back)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def logsumexp_pallas(logits2, interpret=False):
    """One-pass streamed logsumexp over the last axis of (N, V) logits.
    Returns (N,) f32 in base-e units.  Backward is the standard softmax
    pullback as plain jnp (XLA fuses it into the dlogits consumers)."""
    with x64_scope(False):
        return _lse_call(logits2, interpret)[:, 0]


def _lse_vjp_fwd(logits2, interpret):
    with x64_scope(False):
        lse = _lse_call(logits2, interpret)[:, 0]
    return lse, (logits2, lse)


def _lse_vjp_bwd(interpret, res, g):
    logits2, lse = res
    # d lse / d x = softmax(x); per-consumer convert (do NOT bind a full
    # f32 copy of the logits — see loss.py note on CSE materialisation)
    dx = (jnp.exp(logits2.astype(jnp.float32) - lse[:, None])
          * g[:, None]).astype(logits2.dtype)
    return (dx,)


logsumexp_pallas.defvjp(_lse_vjp_fwd, _lse_vjp_bwd)


_lse_register()
