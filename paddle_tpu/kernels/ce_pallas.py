"""Pallas TPU fused softmax-cross-entropy (hard labels).

The reference fuses softmax+CE in one CUDA kernel
(paddle/phi/kernels/gpu/cross_entropy_kernel.cu); the XLA path here is two
streaming reductions (max, sum-exp) plus a gather over the (N, V) logits —
measured ~12 ms/step on the GPT-2 345M bench (V = 50304).  This kernel
computes the row statistics, the label gather AND the loss in one pass over
a VMEM-resident row tile, and the backward writes dlogits directly from the
saved (m, lse) statistics:

    nll_i  = lse_i - logits[i, y_i]
    dlogits[i, v] = (exp(logits[i, v] - lse_i) - 1[v == y_i]) * g_i

Gather-free: the label column is extracted with an iota==label masked sum
(a VPU pass over the resident tile, no scalar loads).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401

DEFAULT_BLOCK_ROWS = 8


def supported(n_rows: int, vocab: int, block_rows: int = DEFAULT_BLOCK_ROWS):
    """Tileability + VMEM budget for the resident (R, V) tile: the bf16
    tile is double-buffered and the kernel's f32 elementwise chain
    materialises ~3 tile-sized temporaries in VMEM."""
    if n_rows <= 0 or vocab % 128 or n_rows % 8:
        return False
    br = _row_block(n_rows)
    if n_rows % br:
        return False
    return br * vocab * (2 * 2 + 4 * 3) <= 10 * 1024 * 1024


def _fwd_kernel(x_ref, y_ref, nll_ref, lse_ref):
    x = x_ref[...].astype(jnp.float32)                   # (R, V)
    y = y_ref[...][:, 0]                                 # (R,) i32
    m = jnp.max(x, axis=-1)
    e = jnp.exp(x - m[:, None])
    lse = m + jnp.log(jnp.sum(e, axis=-1))
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    t = jnp.sum(jnp.where(cols == y[:, None], x, jnp.float32(0.0)), axis=-1)
    nll_ref[...] = (lse - t)[:, None]
    lse_ref[...] = lse[:, None]


def _bwd_kernel(x_ref, y_ref, lse_ref, g_ref, dx_ref):
    x = x_ref[...].astype(jnp.float32)                   # (R, V)
    y = y_ref[...][:, 0]
    lse = lse_ref[...][:, 0]
    g = g_ref[...][:, 0]
    p = jnp.exp(x - lse[:, None])
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    onehot = (cols == y[:, None]).astype(jnp.float32)
    dx_ref[...] = ((p - onehot) * g[:, None]).astype(dx_ref.dtype)


def _row_block(n):
    # DEFAULT_BLOCK_ROWS is the VMEM-bound maximum; with the n % 8 == 0
    # gate this is currently always 8, but keep the shrink for future
    # larger defaults
    br = min(DEFAULT_BLOCK_ROWS, max(n, 1))
    while br > 8 and n % br:
        br //= 2
    return br


def _ce_fwd(x2, y2, interpret):
    n, v = x2.shape
    br = _row_block(n)
    row = pl.BlockSpec((br, 1), lambda i: (i, 0))
    nll, lse = pl.pallas_call(
        _fwd_kernel,
        grid=(n // br,),
        in_specs=[pl.BlockSpec((br, v), lambda i: (i, 0)), row],
        out_specs=[row, row],
        out_shape=[jax.ShapeDtypeStruct((n, 1), jnp.float32)] * 2,
        interpret=interpret,
    )(x2, y2)
    return nll, lse


def _ce_bwd(x2, y2, lse, g, interpret):
    n, v = x2.shape
    br = _row_block(n)
    row = pl.BlockSpec((br, 1), lambda i: (i, 0))
    return pl.pallas_call(
        _bwd_kernel,
        grid=(n // br,),
        in_specs=[pl.BlockSpec((br, v), lambda i: (i, 0)), row, row, row],
        out_specs=pl.BlockSpec((br, v), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, v), x2.dtype),
        interpret=interpret,
    )(x2, y2, lse, g)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def softmax_ce_pallas(logits2, labels2, interpret=False):
    """logits2: (N, V); labels2: (N, 1) int32 (pre-clipped to [0, V)).
    Returns per-row nll (N,) f32."""
    with jax.enable_x64(False):
        nll, _ = _ce_fwd(logits2, labels2, interpret)
    return nll[:, 0]


def _vjp_fwd(logits2, labels2, interpret):
    with jax.enable_x64(False):
        nll, lse = _ce_fwd(logits2, labels2, interpret)
    return nll[:, 0], (logits2, labels2, lse)


def _vjp_bwd(interpret, res, g):
    logits2, labels2, lse = res
    with jax.enable_x64(False):
        dx = _ce_bwd(logits2, labels2, lse,
                     g.astype(jnp.float32)[:, None], interpret)
    return dx, None


softmax_ce_pallas.defvjp(_vjp_fwd, _vjp_bwd)
