"""Pallas TPU flash-attention kernel.

Blockwise streaming-softmax attention (Flash-Attention style): the query
block lives in VMEM, K/V are scanned block-by-block with running (max, sum,
acc) statistics in fp32, so score matrices never materialise in HBM —
O(S) memory instead of the reference FMHA's O(S^2)
(paddle/fluid/operators/fused/fmha_ref.h).

v1 backward = recompute-based custom_vjp (XLA reference attention under
jax.vjp); a dedicated Pallas backward kernel is a later optimisation.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, causal, scale, block_k):
    # q_ref: (1, BQ, D); k_ref/v_ref: (1, S, D); o_ref: (1, BQ, D)
    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    s = k_ref.shape[1]
    # strong int32: program_id is weakly typed and x64 mode would promote
    # its arithmetic to i64, which mosaic cannot lower
    qi = jax.lax.convert_element_type(pl.program_id(1), jnp.int32)

    q = q_ref[0].astype(jnp.float32) * jnp.float32(scale)  # (BQ, D)

    m0 = jnp.full((block_q,), jnp.float32(_NEG_INF), jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    # all index math in explicit-int32 lax ops: under jax x64 mode any
    # python-int mixing can surface i64, which mosaic cannot lower
    i32 = lambda v: jnp.asarray(v, jnp.int32)
    row_ids = jax.lax.mul(qi, i32(block_q))[None, None] + \
        jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(kb, carry):
        m, l, acc = carry
        start = jax.lax.mul(kb, i32(block_k))
        k = k_ref[0, pl.ds(start, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(start, block_k), :].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # (BQ, BK)
        if causal:
            col_ids = start[None, None] + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            logits = jnp.where(col_ids <= row_ids, logits, jnp.float32(_NEG_INF))
        blk_max = jnp.max(logits, axis=-1)
        new_m = jnp.maximum(m, blk_max)
        correction = jnp.exp(m - new_m)
        p = jnp.exp(logits - new_m[:, None])
        new_l = l * correction + jnp.sum(p, axis=-1)
        new_acc = acc * correction[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return new_m, new_l, new_acc

    if causal:
        assert block_q % block_k == 0
        num_kb = jax.lax.mul(jax.lax.add(qi, i32(1)),
                             i32(block_q // block_k))
    else:
        num_kb = i32(s // block_k)
    m, l, acc = jax.lax.fori_loop(i32(0), num_kb, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, jnp.float32(1e-30))[:, None]).astype(o_ref.dtype)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret=False):
    # trace the kernel with x64 off: the global x64 mode (needed for paddle's
    # int64 semantics) surfaces i64/f64 intermediates that mosaic cannot lower
    with jax.enable_x64(False):
        return _flash_fwd_inner(q, k, v, causal, scale, block_q, block_k,
                                interpret)


def _flash_fwd_inner(q, k, v, causal, scale, block_q, block_k, interpret):
    b, h, s, d = q.shape
    bh = b * h
    q3 = q.reshape(bh, s, d)
    k3 = k.reshape(bh, k.shape[2], d)
    v3 = v.reshape(bh, v.shape[2], d)
    nq = s // block_q
    kernel = functools.partial(_fwd_kernel, causal=causal, scale=scale,
                               block_k=block_k)
    out = pl.pallas_call(
        kernel,
        grid=(bh, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bi, i: (bi, i, 0)),
            pl.BlockSpec((1, k3.shape[1], d), lambda bi, i: (bi, 0, 0)),
            pl.BlockSpec((1, v3.shape[1], d), lambda bi, i: (bi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bi, i: (bi, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        interpret=interpret,
    )(q3, k3, v3)
    return out.reshape(b, h, s, d)


def _reference_bhsd(q, k, v, causal, scale):
    logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, _NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_vjp_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    # recompute-based backward: differentiate the XLA reference (remat'd so the
    # S^2 score matrix only exists transiently inside the fused backward)
    _, vjp = jax.vjp(
        jax.checkpoint(lambda q_, k_, v_: _reference_bhsd(q_, k_, v_, causal, scale)),
        q, k, v)
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention_bhsd(q, k, v, causal=False, scale=None,
                         block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                         interpret=False):
    """q,k,v: (B, H, S, D)."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    s = q.shape[2]
    block_q = min(block_q, s)
    block_k = min(block_k, k.shape[2])
    return _flash(q, k, v, causal, float(scale), block_q, block_k, interpret)
