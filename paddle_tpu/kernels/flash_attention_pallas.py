"""Pallas TPU flash-attention kernels (forward + backward).

Blockwise streaming-softmax attention (Flash-Attention style): the query
block lives in VMEM, K/V are scanned block-by-block with running (max, sum,
acc) statistics in fp32, so score matrices never materialise in HBM —
O(S) memory instead of the reference FMHA's O(S^2)
(paddle/fluid/operators/fused/fmha_ref.h).

Backward is a pair of dedicated Pallas kernels (FlashAttention-2 style):
* dQ kernel: grid over query blocks, scans key blocks, recomputes the
  probability block from the saved logsumexp — no O(S^2) materialisation.
* dK/dV kernel: grid over key blocks, scans query blocks.
Both accumulate in fp32 and write grads in the input dtype.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401  (TPU lowering)

def _block_env(name, default):
    """Power-of-two >=128 only: the divisibility-fallback loop in
    flash_attention_bhsd halves the block until it divides the sequence, so
    a non-power-of-two would turn supported() shapes into dispatch errors."""
    raw = os.getenv(name)
    if not raw:
        return default
    try:
        v = int(raw)
    except ValueError:
        return default
    if v < 128 or v & (v - 1):
        return default
    return v


DEFAULT_BLOCK_Q = _block_env("PADDLE_TPU_FLASH_BLOCK_Q", 512)
DEFAULT_BLOCK_K = _block_env("PADDLE_TPU_FLASH_BLOCK_K", 512)
_NEG_INF = -1e30


def _i32(v):
    return jnp.asarray(v, jnp.int32)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal, scale,
                block_k):
    # q_ref: (1, BQ, D); k_ref/v_ref: (1, S, D); o_ref: (1, BQ, D)
    # lse_ref: (1, NQ, BQ) — per-row logsumexp of the scaled (masked)
    # logits, saved for the backward kernels.  The (NQ, BQ) layout is the
    # (S,) row vector folded to satisfy TPU (8,128) tiling: the whole
    # per-(b,h) slice stays resident across the sequential q-block grid
    # steps and each step writes its own row.
    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    s = k_ref.shape[1]
    # strong int32: program_id is weakly typed and x64 mode would promote
    # its arithmetic to i64, which mosaic cannot lower
    qi = jax.lax.convert_element_type(pl.program_id(1), jnp.int32)

    # keep operands in the input dtype (bf16 on the hot path): the MXU's
    # native mode is bf16 x bf16 -> f32 accumulate; upcasting operands to
    # f32 before the dot quarters matmul throughput (measured: the fwd
    # kernel went from ~1.9ms to MXU-bound after this change)
    q = q_ref[0]                                           # (BQ, D)

    m0 = jnp.full((block_q,), jnp.float32(_NEG_INF), jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    row_ids = jax.lax.mul(qi, _i32(block_q))[None, None] + \
        jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def make_body(masked):
        def body(kb, carry):
            m, l, acc = carry
            start = jax.lax.mul(kb, _i32(block_k))
            k = k_ref[0, pl.ds(start, block_k), :]
            v = v_ref[0, pl.ds(start, block_k), :]
            logits = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * jnp.float32(scale)
            if masked:
                col_ids = start[None, None] + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                logits = jnp.where(col_ids <= row_ids, logits,
                                   jnp.float32(_NEG_INF))
            blk_max = jnp.max(logits, axis=-1)
            new_m = jnp.maximum(m, blk_max)
            correction = jnp.exp(m - new_m)
            p = jnp.exp(logits - new_m[:, None])
            new_l = l * correction + jnp.sum(p, axis=-1)
            new_acc = acc * correction[:, None] + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return new_m, new_l, new_acc
        return body

    if causal:
        assert block_q % block_k == 0
        # visible blocks split into fully-visible (no mask arithmetic — the
        # where/iota VPU work is ~half the kernel at these shapes) and the
        # diagonal band (block_q//block_k partially masked blocks)
        ratio = _i32(block_q // block_k)
        num_full = jax.lax.mul(qi, ratio)
        carry = jax.lax.fori_loop(_i32(0), num_full, make_body(False),
                                  (m0, l0, acc0))
        m, l, acc = jax.lax.fori_loop(num_full,
                                      jax.lax.add(num_full, ratio),
                                      make_body(True), carry)
    else:
        num_kb = _i32(s // block_k)
        m, l, acc = jax.lax.fori_loop(_i32(0), num_kb, make_body(False),
                                      (m0, l0, acc0))
    l_safe = jnp.maximum(l, jnp.float32(1e-30))
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0, pl.ds(qi, 1), :] = (m + jnp.log(l_safe))[None, :]


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret=False):
    # trace the kernel with x64 off: the global x64 mode (needed for paddle's
    # int64 semantics) surfaces i64/f64 intermediates that mosaic cannot lower
    with jax.enable_x64(False):
        return _flash_fwd_inner(q, k, v, causal, scale, block_q, block_k,
                                interpret)


def _flash_fwd_inner(q, k, v, causal, scale, block_q, block_k, interpret):
    b, h, s, d = q.shape
    bh = b * h
    q3 = q.reshape(bh, s, d)
    k3 = k.reshape(bh, k.shape[2], d)
    v3 = v.reshape(bh, v.shape[2], d)
    nq = s // block_q
    kernel = functools.partial(_fwd_kernel, causal=causal, scale=scale,
                               block_k=block_k)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bi, i: (bi, i, 0)),
            pl.BlockSpec((1, k3.shape[1], d), lambda bi, i: (bi, 0, 0)),
            pl.BlockSpec((1, v3.shape[1], d), lambda bi, i: (bi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bi, i: (bi, i, 0)),
            pl.BlockSpec((1, nq, block_q), lambda bi, i: (bi, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, nq, block_q), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3)
    return out.reshape(b, h, s, d), lse  # lse stays (bh, nq, block_q)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dq_ref, dk_ref, dv_ref, dq_sc, dk_sc, dv_sc, *,
                causal, scale, nq, nk):
    """Merged FlashAttention-2 backward: ONE kernel produces dQ, dK and dV.

    The textbook two-kernel split (dQ over q-blocks, dK/dV over k-blocks)
    recomputes the logits and dP matmuls twice; merging halves that
    recompute and saves a kernel launch per layer.  Grid = (bh, nk, nq),
    both inner dims sequential: dK/dV accumulate per key block in scratch
    (reset at qi==0), while dQ accumulates across the WHOLE (nk, nq) sweep
    in a full-sequence f32 scratch, written once at the final step.
    q/do (1, BQ, D) stream with qi; k/v (1, BK, D) with ki; lse/delta come
    in the folded (1, NQ, BQ) row layout (see _fwd_kernel)."""
    block_k = k_ref.shape[1]
    block_q = q_ref.shape[1]
    ki = jax.lax.convert_element_type(pl.program_id(1), jnp.int32)
    qi = jax.lax.convert_element_type(pl.program_id(2), jnp.int32)

    @pl.when(jnp.logical_and(ki == 0, qi == 0))
    def _init_dq():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    @pl.when(qi == 0)
    def _init_dkv():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    live = True
    if causal:
        # the block is fully masked iff even its last row precedes the
        # first key column
        live = jax.lax.mul(qi, _i32(block_q)) + _i32(block_q - 1) >= \
            jax.lax.mul(ki, _i32(block_k))

    @pl.when(live)
    def _compute():
        q = q_ref[0]                              # (BQ, D) input dtype
        k = k_ref[0]                              # (BK, D)
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, pl.ds(qi, 1), :][0]      # (BQ,) f32
        delta = delta_ref[0, pl.ds(qi, 1), :][0]  # (BQ,) f32
        logits = jnp.float32(scale) * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)   # (BQ, BK)
        p = jnp.exp(logits - lse[:, None])
        if causal:
            row_ids = jax.lax.mul(qi, _i32(block_q))[None, None] + \
                jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            col_ids = jax.lax.mul(ki, _i32(block_k))[None, None] + \
                jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            p = jnp.where(col_ids <= row_ids, p, jnp.float32(0.0))
        pc = p.astype(do.dtype)
        # dV += P^T dO
        dv_sc[...] = dv_sc[...] + jax.lax.dot_general(
            pc, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)   # (BK, D)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)   # (BQ, BK)
        ds = (p * (dp - delta[:, None])).astype(q.dtype)
        # dK += dS^T Q
        dk_sc[...] = dk_sc[...] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)   # (BK, D)
        # dQ rows qi += dS K
        row0 = jax.lax.mul(qi, _i32(block_q))
        dq_sc[pl.ds(row0, block_q), :] = \
            dq_sc[pl.ds(row0, block_q), :] + jax.lax.dot_general(
                ds, k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize_kv():
        dk_ref[0] = (jnp.float32(scale) * dk_sc[...]).astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[...].astype(dv_ref.dtype)

    @pl.when(jnp.logical_and(ki == nk - 1, qi == nq - 1))
    def _finalize_q():
        dq_ref[0] = (jnp.float32(scale) * dq_sc[...]).astype(dq_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, causal, scale, block_q, block_k,
               interpret=False):
    with jax.enable_x64(False):
        return _flash_bwd_inner(q, k, v, o, lse, do, causal, scale,
                                block_q, block_k, interpret)


def _flash_bwd_inner(q, k, v, o, lse, do, causal, scale, block_q, block_k,
                     interpret):
    b, h, s, d = q.shape
    sk = k.shape[2]
    bh = b * h
    q3 = q.reshape(bh, s, d)
    k3 = k.reshape(bh, sk, d)
    v3 = v.reshape(bh, sk, d)
    do3 = do.reshape(bh, s, d)
    nq = s // block_q
    nk = sk // block_k
    lse3 = lse  # already (bh, nq, block_q), folded row layout
    # delta_i = rowsum(dO_i * O_i) — cheap, fused by XLA; same folded layout
    delta3 = jnp.sum(do3.astype(jnp.float32) *
                     o.reshape(bh, s, d).astype(jnp.float32),
                     axis=-1).reshape(bh, nq, block_q)

    q_spec = pl.BlockSpec((1, block_q, d), lambda bi, i, j: (bi, j, 0))
    k_spec = pl.BlockSpec((1, block_k, d), lambda bi, i, j: (bi, i, 0))
    row_spec = pl.BlockSpec((1, nq, block_q), lambda bi, i, j: (bi, 0, 0))
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_kernel, causal=causal, scale=scale,
                          nq=nq, nk=nk),
        grid=(bh, nk, nq),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
        out_specs=[
            # dq: whole-sequence block, revisited; written at the last step
            pl.BlockSpec((1, s, d), lambda bi, i, j: (bi, 0, 0)),
            k_spec,
            k_spec,
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((s, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(q3, k3, v3, do3, lse3, delta3)

    return (dq.reshape(b, h, s, d), dk.reshape(b, h, sk, d),
            dv.reshape(b, h, sk, d))


# ---------------------------------------------------------------------------
# reference + custom_vjp wiring
# ---------------------------------------------------------------------------

def _reference_bhsd(q, k, v, causal, scale):
    logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, _NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    out, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return out


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    return _flash_bwd(q, k, v, out, lse, g, causal, scale, block_q, block_k,
                      interpret)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention_bhsd(q, k, v, causal=False, scale=None,
                         block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                         interpret=False):
    """q,k,v: (B, H, S, D)."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    s = q.shape[2]
    block_q = min(block_q, s)
    block_k = min(block_k, k.shape[2])
    # shrink to the largest divisible block (the causal kernels also need
    # block_q % block_k == 0, so keep them locked together when possible)
    while block_q > 128 and s % block_q:
        block_q //= 2
    while block_k > 128 and (k.shape[2] % block_k or block_q % block_k):
        block_k //= 2
    if s % block_q or k.shape[2] % block_k:
        raise ValueError(
            "flash_attention: seq lengths (%d, %d) must be divisible by "
            "block sizes (%d, %d) — ragged tails would be silently dropped; "
            "use the XLA path (kernels.flash_attention.supported() gates "
            "this)" % (s, k.shape[2], block_q, block_k))
    return _flash(q, k, v, causal, float(scale), block_q, block_k, interpret)
