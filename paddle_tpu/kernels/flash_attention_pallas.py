"""Pallas TPU flash-attention kernels (forward + backward).

Blockwise streaming-softmax attention (Flash-Attention style): running
(max, sum, acc) statistics in fp32, so score matrices never materialise in
HBM — O(S) memory instead of the reference FMHA's O(S^2)
(paddle/fluid/operators/fused/fmha_ref.h).

Layout: the kernels are NATIVE to the model's (B, S, H, D) activations,
viewed as (B, S, H*D).  Head groups are a GRID dimension over the folded
H*D axis (`hg` heads per cell so hg*D is lane-aligned, i.e. % 128), and the
per-head attention math runs as a static loop inside the cell.  This
removes the six (B,S,H,D) <-> (B,H,S,D) transposes per layer that a
head-major kernel forces around every call — measured ~9 ms/step of pure
HBM copies on the GPT-2 345M bench (PERF.md).

Forward: grid (B, n_hg, nq); the whole K/V sequence stays VMEM-resident and
is scanned with fori loops (measured faster at these shapes than streaming
K/V blocks through the grid — the extra grid steps only added overhead).
Causal q-blocks split the scan into mask-free fully-visible blocks and the
masked diagonal band.

Backward is ONE merged kernel producing dQ, dK and dV: the textbook
two-kernel FlashAttention-2 split recomputes the logits and dP matmuls
twice; merging halves that recompute and saves a launch per layer.
Grid = (B, n_hg, nk, nq) with both inner dims sequential: dK/dV accumulate
per key block in scratch (reset at qi==0), dQ accumulates across the whole
(nk, nq) sweep in a full-sequence f32 scratch written at the final step.
Causal masking skips fully-masked blocks via pl.when (no MXU/VPU work; the
static grid still streams the prefetch, which is the price of pipelining).
A fori-style backward (K/V outer, q scanned inside) was measured SLOWER
(47.6k vs 49.6k tokens/s on the 345M bench) — fwd and bwd optimum differ.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..core.dtype import x64_scope


def _block_env(name, default):
    """Power-of-two >=128 only: the divisibility-fallback loop in
    flash_attention_bshd halves the block until it divides the sequence, so
    a non-power-of-two would turn supported() shapes into dispatch errors."""
    raw = os.getenv(name)
    if not raw:
        return default
    try:
        v = int(raw)
    except ValueError:
        return default
    if v < 128 or v & (v - 1):
        return default
    return v


DEFAULT_BLOCK_Q = _block_env("PADDLE_TPU_FLASH_BLOCK_Q", 512)
DEFAULT_BLOCK_K = _block_env("PADDLE_TPU_FLASH_BLOCK_K", 512)
_NEG_INF = -1e30
# The streaming softmax runs in BASE 2: folding log2(e) into the logits
# scale turns every exp into the VPU's native exp2 (jnp.exp lowers to
# exp2 + a multiply per element, and the softmax exp over b*h*s^2 logits
# is the kernel's dominant VPU cost).  lse is therefore stored in base-2
# units; the backward consumes it with exp2 as well, and d/d(qk) keeps the
# plain base-e `scale` factor (dS = scale * P * (dP - delta) regardless).
_LOG2E = 1.4426950408889634

_SEQ2 = pltpu.CompilerParams(
    dimension_semantics=("parallel", "parallel", "arbitrary", "arbitrary"))

#: A/B flag: mask the causal band by multiplying p after exp2 (max over
#: unmasked logits) instead of the -inf select before it
_BAND_MUL = os.getenv("PADDLE_TPU_FLASH_BANDMUL", "0") == "1"


def _sds(shape, dtype, like):
    """ShapeDtypeStruct carrying the vma (varying-manual-axes) of ``like``
    — pallas_call outputs inside a shard_map must declare how they vary
    (the ring-attention inner runs these kernels under manual axes)."""
    try:
        vma = jax.typeof(like).vma
    except Exception:
        vma = None
    if vma:
        try:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
        except TypeError:
            pass
    return jax.ShapeDtypeStruct(shape, dtype)


def _i32(v):
    return jnp.asarray(v, jnp.int32)


def _pid(i):
    # strong int32: program_id is weakly typed and x64 mode would promote
    # its arithmetic to i64, which mosaic cannot lower
    return jax.lax.convert_element_type(pl.program_id(i), jnp.int32)


# VMEM budget for the forward's resident K+V per grid cell
# (s * hg*d * 2 arrays * 2 B bf16, double-buffered by the pipeline);
# sequences whose K/V exceed it take the grid-streamed forward instead.
_RESIDENT_KV_BUDGET = 4 * 1024 * 1024
# VMEM budget for the backward's full-sequence dq accumulator
# (s * hg*d * 4 B f32) — THE sequence-length bound of the Pallas path;
# beyond it the sequence axis must shard (ring attention, SURVEY §5.7).
# 4MB empirically: 8MB of dq scratch plus streamed blocks + dk/dv scratch
# + lse/delta overflowed the 16MB VMEM by 4.5MB at s=8192.
_DQ_SCRATCH_BUDGET = 4 * 1024 * 1024


def _aligned_groups(h: int, d: int):
    out = [hg for hg in (8, 4, 2, 1)
           if h % hg == 0 and (hg * d) % 128 == 0]
    if not out:
        out = [h]  # whole folded axis: legal regardless of alignment
    return out


def _pick_head_group(h: int, d: int, s: int):
    """Heads per grid cell: hg*d must be lane-aligned (%128) and divide h.
    Picks the LARGEST group with hg*d <= 256 — bigger groups amortize grid
    overhead (+0.8k tokens/s measured on the 345M bench; hg*d=512 blew
    VMEM by 156KB at s=1024) — whose backward dq scratch still fits at this
    sequence length (long sequences shrink the group)."""
    def bwd_fits(hg):
        return s * hg * d * 4 <= _DQ_SCRATCH_BUDGET

    forced = _valid_forced_group(h, d)
    if forced is not None:
        return forced
    groups = _aligned_groups(h, d)
    for hg in groups:            # largest first
        if hg * d <= 256 and bwd_fits(hg):
            return hg
    # no group fits the merged backward's full-seq scratch: the SPLIT
    # backward (O(block) VMEM) takes over — pick by block size alone
    for hg in groups:
        if hg * d <= 256:
            return hg
    return groups[-1]


def _kv_fits_resident(s: int, hgd: int) -> bool:
    """K+V bf16, double-buffered — must match _flash_fwd_inner's dispatch
    between the resident and streamed forward."""
    return s * hgd * 2 * 2 <= _RESIDENT_KV_BUDGET


def _valid_forced_group(h: int, d: int):
    raw = os.getenv("PADDLE_TPU_FLASH_HEAD_GROUP")
    if not raw:
        return None
    try:
        hg = int(raw)
    except ValueError:
        return None
    if h % hg == 0 and ((hg * d) % 128 == 0 or hg == h):
        return hg
    return None


def _pick_fwd_head_group(h: int, d: int, s: int, hg_b: int) -> int:
    """The forward has no full-sequence scratch, so it can afford a larger
    group (up to hg*d = 512) when the resident K/V still fits — fewer grid
    cells amortize per-cell overhead.  Falls back to the backward's group.
    A VALID env override (PADDLE_TPU_FLASH_HEAD_GROUP) pins both
    directions; invalid values are ignored in both pickers."""
    if _valid_forced_group(h, d) is not None:
        return hg_b
    for hg in _aligned_groups(h, d):      # largest first
        if hg * d <= 512 and _kv_fits_resident(s, hg * d):
            # the first admissible candidate is always >= hg_b (hg_b
            # satisfies stricter constraints), so no max() needed
            return hg
    return hg_b


#: VMEM allowance for the full-sequence lse+delta blocks the kernels keep
#: resident per grid cell ((1,1,hg,nq,bq) each = hg*s*4 B); the rest of
#: the 16 MB budget is operand blocks + scratch + double buffering
_LSE_RESIDENCY_BUDGET = 8 * 1024 * 1024


def max_supported_seq(h: int, d: int) -> int:
    """Longest sequence the Pallas path supports end-to-end, derived from
    the lse/delta VMEM residency at THIS (h, d)'s head group — a flat cap
    admitted shapes (e.g. d=32 -> hg=8) whose hg*s*4-byte lse blocks fail
    Mosaic allocation at compile time (ADVICE r3).  Beyond the cap the
    sequence axis should shard (ring/Ulysses, SURVEY §5.7)."""
    s = 256 * 1024
    while s >= 1024:
        hg = _pick_head_group(h, d, s)
        if 2 * hg * s * 4 <= _LSE_RESIDENCY_BUDGET:
            return s
        s //= 2
    return 1024


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal, scale, hg,
                d, block_k):
    # q/o: (1, BQ, HG*D); k/v: (1, S, HG*D) — the WHOLE sequence resident
    # in VMEM, scanned with a fori loop (measured faster than grid-streamed
    # K/V blocks at these shapes: the pipeline only added grid overhead);
    # lse: (1, 1, HG, NQ, BQ).
    block_q = q_ref.shape[1]
    s = k_ref.shape[1]
    qi = _pid(2)

    row_ids = jax.lax.mul(qi, _i32(block_q))[None, None] + \
        jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    for hh in range(hg):
        sl = slice(hh * d, (hh + 1) * d)
        q = q_ref[0, :, sl]                                   # (BQ, D)

        def make_body(masked):
            def body(kb, carry):
                m, l, acc = carry
                start = jax.lax.mul(kb, _i32(block_k))
                k = k_ref[0, pl.ds(start, block_k), sl]
                v = v_ref[0, pl.ds(start, block_k), sl]
                # bf16 x bf16 -> f32 is the MXU's native mode; upcasting
                # operands first quarters matmul throughput
                logits = jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32) * \
                    jnp.float32(scale * _LOG2E)
                band_mul = masked and _BAND_MUL
                if masked:
                    col_ids = start[None, None] + \
                        jax.lax.broadcasted_iota(
                            jnp.int32, (block_q, block_k), 1)
                    vis = col_ids <= row_ids
                    if not band_mul:
                        logits = jnp.where(vis, logits,
                                           jnp.float32(_NEG_INF))
                # band_mul (PADDLE_TPU_FLASH_BANDMUL=1): run the max over
                # UNMASKED logits (an over-estimate only shrinks p — lse
                # stays exact) and zero the future columns AFTER the exp2
                # with one multiply, replacing the -inf select
                new_m = jnp.maximum(m, jnp.max(logits, axis=-1))
                correction = jnp.exp2(m - new_m)
                p = jnp.exp2(logits - new_m[:, None])
                if band_mul:
                    p = p * vis.astype(jnp.float32)
                new_l = l * correction + jnp.sum(p, axis=-1)
                new_acc = acc * correction[:, None] + jax.lax.dot_general(
                    p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                return new_m, new_l, new_acc
            return body

        init = (jnp.full((block_q,), jnp.float32(_NEG_INF), jnp.float32),
                jnp.zeros((block_q,), jnp.float32),
                jnp.zeros((block_q, d), jnp.float32))
        if causal:
            # fully-visible blocks skip the mask arithmetic; the diagonal
            # band (block_q // block_k blocks) applies it
            assert block_q % block_k == 0
            ratio = _i32(block_q // block_k)
            num_full = jax.lax.mul(qi, ratio)
            carry = jax.lax.fori_loop(_i32(0), num_full, make_body(False),
                                      init)
            m, l, acc = jax.lax.fori_loop(num_full,
                                          jax.lax.add(num_full, ratio),
                                          make_body(True), carry)
        else:
            m, l, acc = jax.lax.fori_loop(_i32(0), _i32(s // block_k),
                                          make_body(False), init)
        l_safe = jnp.maximum(l, jnp.float32(1e-30))
        o_ref[0, :, sl] = (acc / l_safe[:, None]).astype(o_ref.dtype)
        # lse in base-2 units: m is already log2-scaled
        lse_ref[0, 0, hh, pl.ds(qi, 1), :] = \
            (m + jnp.log(l_safe) * jnp.float32(_LOG2E))[None, :]


def _fwd_kernel_streamed(q_ref, k_ref, v_ref, o_ref, lse_ref, m_sc, l_sc, acc_sc, *,
                causal, scale, hg, d, nk):
    # q/o: (1, BQ, HG*D); k/v: (1, BK, HG*D) — ki-th block, streamed by the
    # grid; lse: (1, 1, HG, NQ, BQ); scratch m/l: (HG, BQ) f32,
    # acc: (BQ, HG*D) f32, persistent across the sequential ki iterations.
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]
    qi = _pid(2)
    ki = _pid(3)

    @pl.when(ki == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    def _attend(masked):
        if masked:
            row_ids = jax.lax.mul(qi, _i32(block_q))[None, None] + \
                jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            col_ids = jax.lax.mul(ki, _i32(block_k))[None, None] + \
                jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = col_ids <= row_ids
        for hh in range(hg):
            sl = slice(hh * d, (hh + 1) * d)
            q = q_ref[0, :, sl]                               # (BQ, D)
            k = k_ref[0, :, sl]                               # (BK, D)
            v = v_ref[0, :, sl]
            # bf16 x bf16 -> f32 is the MXU's native mode; upcasting
            # operands first quarters matmul throughput
            logits = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * \
                jnp.float32(scale * _LOG2E)
            if masked:
                logits = jnp.where(mask, logits, jnp.float32(_NEG_INF))
            m = m_sc[hh]
            new_m = jnp.maximum(m, jnp.max(logits, axis=-1))
            correction = jnp.exp2(m - new_m)
            p = jnp.exp2(logits - new_m[:, None])
            l_sc[hh] = l_sc[hh] * correction + jnp.sum(p, axis=-1)
            acc_sc[:, sl] = acc_sc[:, sl] * correction[:, None] + \
                jax.lax.dot_general(
                    p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
            m_sc[hh] = new_m

    if causal:
        # split visible blocks into fully-visible (no mask arithmetic —
        # the iota/where VPU work is significant at these shapes) and the
        # diagonal band (masked); the two pl.when branches are disjoint
        first_row = jax.lax.mul(qi, _i32(block_q))
        last_row = first_row + _i32(block_q - 1)
        last_col = jax.lax.mul(ki, _i32(block_k)) + _i32(block_k - 1)
        fully_visible = last_col <= first_row
        diagonal = jnp.logical_and(last_col > first_row,
                                   jax.lax.mul(ki, _i32(block_k)) <=
                                   last_row)

        @pl.when(fully_visible)
        def _compute_full():
            _attend(False)

        @pl.when(diagonal)
        def _compute_diag():
            _attend(True)
    else:
        _attend(False)

    @pl.when(ki == nk - 1)
    def _finalize():
        for hh in range(hg):
            sl = slice(hh * d, (hh + 1) * d)
            l_safe = jnp.maximum(l_sc[hh], jnp.float32(1e-30))
            o_ref[0, :, sl] = (acc_sc[:, sl] /
                               l_safe[:, None]).astype(o_ref.dtype)
            # lse in base-2 units (see _LOG2E)
            lse_ref[0, 0, hh, pl.ds(qi, 1), :] = \
                (m_sc[hh] + jnp.log(l_safe) * jnp.float32(_LOG2E))[None, :]



def _flash_fwd(q3, k3, v3, causal, scale, block_q, block_k, hg, d,
               interpret=False):
    # trace with x64 off: the global x64 mode (needed for paddle's int64
    # semantics) surfaces i64/f64 intermediates that mosaic cannot lower
    with x64_scope(False):
        return _flash_fwd_inner(q3, k3, v3, causal, scale, block_q, block_k,
                                hg, d, interpret)


def _flash_fwd_inner(q3, k3, v3, causal, scale, block_q, block_k, hg, d,
                     interpret):
    b, s, hd = q3.shape
    sk = k3.shape[1]
    n_hg = hd // (hg * d)
    nq = s // block_q
    nk = sk // block_k
    hgd = hg * d
    q_spec3 = pl.BlockSpec((1, block_q, hgd), lambda bi, g, i: (bi, i, g))
    lse_shape = _sds((b, n_hg, hg, nq, block_q), jnp.float32, q3)
    out_shape = _sds((b, s, hd), q3.dtype, q3)
    if _kv_fits_resident(sk, hgd):
        # fast path: whole K/V resident per cell, fori scan (measured
        # fastest at bench shapes)
        kernel = functools.partial(_fwd_kernel, causal=causal, scale=scale,
                                   hg=hg, d=d, block_k=block_k)
        kv_spec = pl.BlockSpec((1, sk, hgd), lambda bi, g, i: (bi, 0, g))
        out, lse = pl.pallas_call(
            kernel,
            grid=(b, n_hg, nq),
            in_specs=[q_spec3, kv_spec, kv_spec],
            out_specs=[
                q_spec3,
                # whole folded lse slice per (b, head-group), revisited
                # across the sequential q-block dim
                pl.BlockSpec((1, 1, hg, nq, block_q),
                             lambda bi, g, i: (bi, g, 0, 0, 0)),
            ],
            out_shape=[out_shape, lse_shape],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=interpret,
        )(q3, k3, v3)
        return out, lse
    # long-sequence path: K/V blocks streamed by the grid — O(block) VMEM,
    # keeps the O(S) capability for sequences whose K/V don't fit resident
    kernel = functools.partial(_fwd_kernel_streamed, causal=causal,
                               scale=scale, hg=hg, d=d, nk=nk)
    q_spec = pl.BlockSpec((1, block_q, hgd), lambda bi, g, i, j: (bi, i, g))
    kv_spec = pl.BlockSpec((1, block_k, hgd), lambda bi, g, i, j: (bi, j, g))
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, n_hg, nq, nk),
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=[
            q_spec,
            pl.BlockSpec((1, 1, hg, nq, block_q),
                         lambda bi, g, i, j: (bi, g, 0, 0, 0)),
        ],
        out_shape=[out_shape, lse_shape],
        scratch_shapes=[
            pltpu.VMEM((hg, block_q), jnp.float32),
            pltpu.VMEM((hg, block_q), jnp.float32),
            pltpu.VMEM((block_q, hgd), jnp.float32),
        ],
        compiler_params=_SEQ2,
        interpret=interpret,
    )(q3, k3, v3)
    return out, lse


# ---------------------------------------------------------------------------
# backward (merged dQ/dK/dV)
# ---------------------------------------------------------------------------

def _apply_causal_split(compute, causal, qi, ki, block_q, block_k):
    """Run ``compute(masked)`` under the causal block taxonomy: skipped
    (strictly-future), fully-visible (no mask arithmetic), or diagonal
    band (mask applied).  Non-causal runs unconditionally unmasked."""
    if not causal:
        compute(False)
        return
    first_row = jax.lax.mul(qi, _i32(block_q))
    last_row = first_row + _i32(block_q - 1)
    first_col = jax.lax.mul(ki, _i32(block_k))
    last_col = first_col + _i32(block_k - 1)
    fully_visible = last_col <= first_row
    diagonal = jnp.logical_and(last_col > first_row, first_col <= last_row)
    pl.when(fully_visible)(lambda: compute(False))
    pl.when(diagonal)(lambda: compute(True))


def _bwd_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dq_ref, dk_ref, dv_ref, dq_sc, dk_sc, dv_sc, *,
                causal, scale, hg, d, nq, nk):
    block_k = k_ref.shape[1]
    block_q = q_ref.shape[1]
    ki = _pid(2)
    qi = _pid(3)

    @pl.when(jnp.logical_and(ki == 0, qi == 0))
    def _init_dq():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    @pl.when(qi == 0)
    def _init_dkv():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    def _compute(masked):
        if masked:
            row_ids = jax.lax.mul(qi, _i32(block_q))[None, None] + \
                jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            col_ids = jax.lax.mul(ki, _i32(block_k))[None, None] + \
                jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = col_ids <= row_ids
        row0 = jax.lax.mul(qi, _i32(block_q))
        for hh in range(hg):
            sl = slice(hh * d, (hh + 1) * d)
            q = q_ref[0, :, sl]                       # (BQ, D) input dtype
            k = k_ref[0, :, sl]                       # (BK, D)
            v = v_ref[0, :, sl]
            do = do_ref[0, :, sl]
            lse = lse_ref[0, 0, hh, pl.ds(qi, 1), :][0]      # (BQ,) f32, base-2
            delta = delta_ref[0, 0, hh, pl.ds(qi, 1), :][0]  # (BQ,) f32
            logits = jnp.float32(scale * _LOG2E) * jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)          # (BQ, BK)
            p = jnp.exp2(logits - lse[:, None])
            if masked:
                p = jnp.where(mask, p, jnp.float32(0.0))
            pc = p.astype(do.dtype)
            # dV += P^T dO
            dv_sc[:, sl] = dv_sc[:, sl] + jax.lax.dot_general(
                pc, do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)          # (BK, D)
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)          # (BQ, BK)
            ds = (p * (dp - delta[:, None])).astype(q.dtype)
            # dK += dS^T Q
            dk_sc[:, sl] = dk_sc[:, sl] + jax.lax.dot_general(
                ds, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)          # (BK, D)
            # dQ rows qi += dS K
            dq_sc[pl.ds(row0, block_q), sl] = \
                dq_sc[pl.ds(row0, block_q), sl] + jax.lax.dot_general(
                    ds, k, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)

    # fully-visible blocks skip the iota/where mask arithmetic entirely —
    # only the diagonal band pays it (the same split the streamed forward
    # uses; the two pl.when conditions are disjoint)
    _apply_causal_split(_compute, causal, qi, ki, block_q, block_k)

    @pl.when(qi == nq - 1)
    def _finalize_kv():
        dk_ref[0] = (jnp.float32(scale) * dk_sc[...]).astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[...].astype(dv_ref.dtype)

    @pl.when(jnp.logical_and(ki == nk - 1, qi == nq - 1))
    def _finalize_q():
        dq_ref[0] = (jnp.float32(scale) * dq_sc[...]).astype(dq_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_sc, *, causal, scale, hg, d, nk):
    """dQ-only backward for LONG sequences: grid (b, n_hg, nq, nk) with ki
    innermost, so dq accumulates in a BLOCK-sized scratch (no full-sequence
    scratch — the merged kernel's 16k+ VMEM blocker, PERF.md)."""
    block_k = k_ref.shape[1]
    block_q = q_ref.shape[1]
    qi = _pid(2)
    ki = _pid(3)

    @pl.when(ki == 0)
    def _init():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    def _compute(masked):
        if masked:
            row_ids = jax.lax.mul(qi, _i32(block_q))[None, None] + \
                jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            col_ids = jax.lax.mul(ki, _i32(block_k))[None, None] + \
                jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = col_ids <= row_ids
        for hh in range(hg):
            sl = slice(hh * d, (hh + 1) * d)
            q = q_ref[0, :, sl]
            k = k_ref[0, :, sl]
            v = v_ref[0, :, sl]
            do = do_ref[0, :, sl]
            lse = lse_ref[0, 0, hh, pl.ds(qi, 1), :][0]      # base-2
            delta = delta_ref[0, 0, hh, pl.ds(qi, 1), :][0]
            logits = jnp.float32(scale * _LOG2E) * jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            p = jnp.exp2(logits - lse[:, None])
            if masked:
                p = jnp.where(mask, p, jnp.float32(0.0))
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = (p * (dp - delta[:, None])).astype(q.dtype)
            dq_sc[:, sl] = dq_sc[:, sl] + jax.lax.dot_general(
                ds, k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    _apply_causal_split(_compute, causal, qi, ki, block_q, block_k)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = (jnp.float32(scale) * dq_sc[...]).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_sc, dv_sc, *, causal, scale, hg, d,
                    nq):
    """dK/dV backward (ki outer, qi inner) — the merged kernel minus the
    full-sequence dq scratch; pairs with _bwd_dq_kernel for long seqs."""
    block_k = k_ref.shape[1]
    block_q = q_ref.shape[1]
    ki = _pid(2)
    qi = _pid(3)

    @pl.when(qi == 0)
    def _init():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    def _compute(masked):
        if masked:
            row_ids = jax.lax.mul(qi, _i32(block_q))[None, None] + \
                jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            col_ids = jax.lax.mul(ki, _i32(block_k))[None, None] + \
                jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = col_ids <= row_ids
        for hh in range(hg):
            sl = slice(hh * d, (hh + 1) * d)
            q = q_ref[0, :, sl]
            k = k_ref[0, :, sl]
            v = v_ref[0, :, sl]
            do = do_ref[0, :, sl]
            lse = lse_ref[0, 0, hh, pl.ds(qi, 1), :][0]
            delta = delta_ref[0, 0, hh, pl.ds(qi, 1), :][0]
            logits = jnp.float32(scale * _LOG2E) * jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            p = jnp.exp2(logits - lse[:, None])
            if masked:
                p = jnp.where(mask, p, jnp.float32(0.0))
            pc = p.astype(do.dtype)
            dv_sc[:, sl] = dv_sc[:, sl] + jax.lax.dot_general(
                pc, do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = (p * (dp - delta[:, None])).astype(q.dtype)
            dk_sc[:, sl] = dk_sc[:, sl] + jax.lax.dot_general(
                ds, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    _apply_causal_split(_compute, causal, qi, ki, block_q, block_k)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = (jnp.float32(scale) * dk_sc[...]).astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[...].astype(dv_ref.dtype)


def _flash_bwd_split(q3, k3, v3, o3, lse, do3, causal, scale, block_q,
                     block_k, hg, d, interpret, dlse=None):
    """Two-kernel backward with O(block) VMEM — the long-sequence path
    (the merged kernel's full-sequence dq scratch caps it at ~8k tokens).
    Costs one extra recompute of the logits/dP matmuls per block pair."""
    b, s, hd = q3.shape
    sk = k3.shape[1]
    h = hd // d
    n_hg = h // hg
    nq = s // block_q
    nk = sk // block_k
    hgd = hg * d
    delta = jnp.sum(
        do3.reshape(b, s, h, d).astype(jnp.float32) *
        o3.reshape(b, s, h, d).astype(jnp.float32), axis=-1)
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)
    delta = jnp.moveaxis(delta, -1, 1).reshape(b, n_hg, hg, nq, block_q)

    row_spec = pl.BlockSpec((1, 1, hg, nq, block_q),
                            lambda bi, g, i, j: (bi, g, 0, 0, 0))
    q_spec_qout = pl.BlockSpec((1, block_q, hgd),
                               lambda bi, g, i, j: (bi, i, g))
    kv_spec_qout = pl.BlockSpec((1, block_k, hgd),
                                lambda bi, g, i, j: (bi, j, g))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, scale=scale,
                          hg=hg, d=d, nk=nk),
        grid=(b, n_hg, nq, nk),
        in_specs=[q_spec_qout, kv_spec_qout, kv_spec_qout, q_spec_qout,
                  row_spec, row_spec],
        out_specs=q_spec_qout,
        out_shape=_sds((b, s, hd), q3.dtype, q3),
        scratch_shapes=[pltpu.VMEM((block_q, hgd), jnp.float32)],
        compiler_params=_SEQ2,
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)

    q_spec_kout = pl.BlockSpec((1, block_q, hgd),
                               lambda bi, g, i, j: (bi, j, g))
    kv_spec_kout = pl.BlockSpec((1, block_k, hgd),
                                lambda bi, g, i, j: (bi, i, g))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, scale=scale,
                          hg=hg, d=d, nq=nq),
        grid=(b, n_hg, nk, nq),
        in_specs=[q_spec_kout, kv_spec_kout, kv_spec_kout, q_spec_kout,
                  row_spec, row_spec],
        out_specs=[kv_spec_kout, kv_spec_kout],
        out_shape=[_sds((b, sk, hd), k3.dtype, k3),
                   _sds((b, sk, hd), v3.dtype, v3)],
        scratch_shapes=[pltpu.VMEM((block_k, hgd), jnp.float32),
                        pltpu.VMEM((block_k, hgd), jnp.float32)],
        compiler_params=_SEQ2,
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)
    return dq, dk, dv


def _flash_bwd(q3, k3, v3, o3, lse, do3, causal, scale, block_q, block_k,
               hg, d, interpret=False, dlse=None):
    # dlse: optional (b, s, h) f32 cotangent of a base-e lse OUTPUT
    # (flash_attention_bshd_with_lse): it folds into the kernels as
    # delta - dlse — dS_ij = P_ij (dP_ij - delta_i + dlse_i), so the
    # existing kernels run unchanged
    with x64_scope(False):
        s = max(q3.shape[1], k3.shape[1])
        if s * hg * d * 4 > _DQ_SCRATCH_BUDGET:
            # long sequence: the merged kernel's full-seq dq scratch would
            # blow VMEM — take the split two-kernel path
            return _flash_bwd_split(q3, k3, v3, o3, lse, do3, causal,
                                    scale, block_q, block_k, hg, d,
                                    interpret, dlse)
        return _flash_bwd_inner(q3, k3, v3, o3, lse, do3, causal, scale,
                                block_q, block_k, hg, d, interpret, dlse)


def _flash_bwd_inner(q3, k3, v3, o3, lse, do3, causal, scale, block_q,
                     block_k, hg, d, interpret, dlse=None):
    b, s, hd = q3.shape
    sk = k3.shape[1]
    h = hd // d
    n_hg = h // hg
    nq = s // block_q
    nk = sk // block_k
    hgd = hg * d
    # delta = rowsum(dO * O) per head — cheap, fused by XLA; folded to the
    # same (b, n_hg, hg, nq, bq) row layout as lse
    delta = jnp.sum(
        do3.reshape(b, s, h, d).astype(jnp.float32) *
        o3.reshape(b, s, h, d).astype(jnp.float32), axis=-1)       # (b,s,h)
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)
    delta = jnp.moveaxis(delta, -1, 1).reshape(b, n_hg, hg, nq, block_q)

    q_spec = pl.BlockSpec((1, block_q, hgd), lambda bi, g, i, j: (bi, j, g))
    kv_spec = pl.BlockSpec((1, block_k, hgd), lambda bi, g, i, j: (bi, i, g))
    row_spec = pl.BlockSpec((1, 1, hg, nq, block_q),
                            lambda bi, g, i, j: (bi, g, 0, 0, 0))
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_kernel, causal=causal, scale=scale,
                          hg=hg, d=d, nq=nq, nk=nk),
        grid=(b, n_hg, nk, nq),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=[
            # dq: whole-sequence block, revisited; written at the last step
            pl.BlockSpec((1, s, hgd), lambda bi, g, i, j: (bi, 0, g)),
            kv_spec,
            kv_spec,
        ],
        out_shape=[
            _sds((b, s, hd), q3.dtype, q3),
            _sds((b, sk, hd), k3.dtype, k3),
            _sds((b, sk, hd), v3.dtype, v3),
        ],
        scratch_shapes=[
            pltpu.VMEM((s, hgd), jnp.float32),
            pltpu.VMEM((block_k, hgd), jnp.float32),
            pltpu.VMEM((block_k, hgd), jnp.float32),
        ],
        compiler_params=_SEQ2,
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# reference + custom_vjp wiring
# ---------------------------------------------------------------------------

def _reference_bhsd(q, k, v, causal, scale):
    logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, _NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10))
def _flash(q3, k3, v3, causal, scale, block_q, block_k, hg_f, hg_b, d,
           interpret):
    # hg_f / hg_b: independent head groups for forward and backward — the
    # backward's full-sequence dq scratch binds its group size, while the
    # forward can amortize more heads per grid cell
    out, _ = _flash_fwd(q3, k3, v3, causal, scale, block_q, block_k, hg_f,
                        d, interpret)
    return out


def _flash_vjp_fwd(q3, k3, v3, causal, scale, block_q, block_k, hg_f, hg_b,
                   d, interpret):
    out, lse = _flash_fwd(q3, k3, v3, causal, scale, block_q, block_k, hg_f,
                          d, interpret)
    return out, (q3, k3, v3, out, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_k, hg_f, hg_b, d,
                   interpret, res, g):
    q3, k3, v3, out, lse = res
    if hg_b != hg_f:
        # regroup the folded lse rows (b, h/hg_f, hg_f, nq, bq) ->
        # (b, h/hg_b, hg_b, nq, bq): contiguous reshape, no data movement
        b = lse.shape[0]
        nq, bq = lse.shape[3], lse.shape[4]
        h = lse.shape[1] * lse.shape[2]
        lse = lse.reshape(b, h // hg_b, hg_b, nq, bq)
    return _flash_bwd(q3, k3, v3, out, lse, g, causal, scale, block_q,
                      block_k, hg_b, d, interpret)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _prep_blocks(q, k, causal, block_q, block_k, what):
    """Shared block/head-group policy of the public BSHD wrappers: shrink
    to the largest divisible power-of-two blocks (>=128), cap block_k at
    block_q under causal (the band split needs block_q %% block_k == 0),
    and raise on ragged tails."""
    b, s, h, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, s)
    block_k = min(block_k, sk)
    while block_q > 128 and s % block_q:
        block_q //= 2
    while block_k > 128 and sk % block_k:
        block_k //= 2
    if causal and block_k > block_q:
        block_k = block_q
    if s % block_q or sk % block_k:
        raise ValueError(
            "%s: seq lengths (%d, %d) must be divisible by block sizes "
            "(%d, %d) — ragged tails would be silently dropped; use the "
            "XLA path (kernels.flash_attention.supported() gates this)"
            % (what, s, sk, block_q, block_k))
    return block_q, block_k


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9,
                                                    10))
def _flash_lse(q3, k3, v3, causal, scale, block_q, block_k, hg_f, hg_b, d,
               interpret):
    out, lse2 = _flash_fwd(q3, k3, v3, causal, scale, block_q, block_k,
                           hg_f, d, interpret)
    return out, lse2


def _flash_lse_vjp_fwd(q3, k3, v3, causal, scale, block_q, block_k, hg_f,
                       hg_b, d, interpret):
    out, lse2 = _flash_fwd(q3, k3, v3, causal, scale, block_q, block_k,
                           hg_f, d, interpret)
    return (out, lse2), (q3, k3, v3, out, lse2)


def _flash_lse_vjp_bwd(causal, scale, block_q, block_k, hg_f, hg_b, d,
                       interpret, res, g):
    q3, k3, v3, out, lse2 = res
    dout, dlse2 = g
    b, s, hd = q3.shape
    h = hd // d
    # unfold the (b, n_hg, hg, nq, bq) base-2 lse cotangent to (b, s, h)
    # base-e: lse2 = lse_e * log2e, so dlse_e = dlse2 * log2e
    dlse = jnp.moveaxis(
        dlse2.reshape(b, h, s), 1, -1) * jnp.float32(_LOG2E)
    lse = lse2
    if hg_b != hg_f:
        nq, bq = lse.shape[3], lse.shape[4]
        lse = lse.reshape(b, h // hg_b, hg_b, nq, bq)
    return _flash_bwd(q3, k3, v3, out, lse, dout, causal, scale, block_q,
                      block_k, hg_b, d, interpret, dlse=dlse)


_flash_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


def flash_attention_bshd_with_lse(q, k, v, causal=False, scale=None,
                                  block_q=DEFAULT_BLOCK_Q,
                                  block_k=DEFAULT_BLOCK_K,
                                  interpret=False):
    """Like :func:`flash_attention_bshd_native` but ALSO returns the
    row logsumexp in BASE E, shape (B, S, H) — and stays differentiable
    when the caller consumes both (the lse cotangent folds into the
    backward kernels as ``delta - dlse``).  This is the building block
    the ring-attention inner needs (r4 verdict #3): per-shard
    (out, lse) pairs combine exactly like global attention."""
    b, s, h, d = q.shape
    sk = k.shape[1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    hg_b = _pick_head_group(h, d, max(s, sk))
    hg_f = _pick_fwd_head_group(h, d, max(s, sk), hg_b)
    if hg_f != hg_b:
        # one group for both directions: the lse OUTPUT layout must match
        # what the backward consumes (the fwd/bwd regroup trick in
        # _flash_vjp_bwd assumes lse is internal)
        hg_f = hg_b
    block_q, block_k = _prep_blocks(q, k, causal, block_q, block_k,
                                    "flash_attention_with_lse")
    q3 = q.reshape(b, s, h * d)
    k3 = k.reshape(b, sk, h * d)
    v3 = v.reshape(b, sk, h * d)
    out, lse2 = _flash_lse(q3, k3, v3, causal, float(scale), block_q,
                           block_k, hg_f, hg_b, d, interpret)
    # (b, n_hg, hg, nq, bq) base-2 -> (b, s, h) base-e
    lse = jnp.moveaxis(lse2.reshape(b, h, s), 1, -1) / jnp.float32(_LOG2E)
    return out.reshape(b, s, h, d), lse


def flash_attention_bshd_native(q, k, v, causal=False, scale=None,
                                block_q=DEFAULT_BLOCK_Q,
                                block_k=DEFAULT_BLOCK_K, interpret=False):
    """q,k,v: (B, S, H, D) — the model's native layout; no transposes."""
    b, s, h, d = q.shape
    sk = k.shape[1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    hg_b = _pick_head_group(h, d, max(s, sk))
    hg_f = _pick_fwd_head_group(h, d, max(s, sk), hg_b)
    block_q, block_k = _prep_blocks(q, k, causal, block_q, block_k,
                                    "flash_attention")
    q3 = q.reshape(b, s, h * d)
    k3 = k.reshape(b, sk, h * d)
    v3 = v.reshape(b, sk, h * d)
    out = _flash(q3, k3, v3, causal, float(scale), block_q, block_k, hg_f,
                 hg_b, d, interpret)
    return out.reshape(b, s, h, d)


def flash_attention_bhsd(q, k, v, causal=False, scale=None,
                         block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                         interpret=False):
    """q,k,v: (B, H, S, D) — compat wrapper over the native BSHD kernel
    (introduces two transposes; the model path uses BSHD directly)."""
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out = flash_attention_bshd_native(qt, kt, vt, causal=causal, scale=scale,
                                      block_q=block_q, block_k=block_k,
                                      interpret=interpret)
    return jnp.swapaxes(out, 1, 2)
