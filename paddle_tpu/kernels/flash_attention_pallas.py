"""Pallas TPU flash-attention kernels (forward + backward).

Blockwise streaming-softmax attention (Flash-Attention style): running
(max, sum, acc) statistics in fp32, so score matrices never materialise in
HBM — O(S) memory instead of the reference FMHA's O(S^2)
(paddle/fluid/operators/fused/fmha_ref.h).

Layout: the kernels are NATIVE to the model's (B, S, H, D) activations,
viewed as (B, S, H*D).  Head groups are a GRID dimension over the folded
H*D axis (`hg` heads per cell so hg*D is lane-aligned, i.e. % 128), and the
per-head attention math runs as a static loop inside the cell.  This
removes the six (B,S,H,D) <-> (B,H,S,D) transposes per layer that a
head-major kernel forces around every call — measured ~9 ms/step of pure
HBM copies on the GPT-2 345M bench (PERF.md).

Forward: grid (B, n_hg, nq); the whole K/V sequence stays VMEM-resident and
is scanned with fori loops (measured faster at these shapes than streaming
K/V blocks through the grid — the extra grid steps only added overhead).
Causal q-blocks split the scan into mask-free fully-visible blocks and the
masked diagonal band.

Backward is ONE merged kernel producing dQ, dK and dV: the textbook
two-kernel FlashAttention-2 split recomputes the logits and dP matmuls
twice; merging halves that recompute and saves a launch per layer.
Grid = (B, n_hg, nk, nq) with both inner dims sequential: dK/dV accumulate
per key block in scratch (reset at qi==0), dQ accumulates across the whole
(nk, nq) sweep in a full-sequence f32 scratch written at the final step.
Causal masking skips fully-masked blocks via pl.when (no MXU/VPU work; the
static grid still streams the prefetch, which is the price of pipelining).
A fori-style backward (K/V outer, q scanned inside) was measured SLOWER
(47.6k vs 49.6k tokens/s on the 345M bench) — fwd and bwd optimum differ.

Variants (round 6): every kernel family is registered with the autotuner
(kernels/autotune.py) and the softmax/mask/pipeline machinery is variant-
selectable — the hand-tuned round-5 configuration is the "base" variant and
the default, so nothing changes until tuning runs or a config is pinned:

- ``bf16chain``: the streaming-softmax elementwise chain (mask select,
  running max, exp2, p) runs in bf16 — the VPU's 2x-throughput dtype — with
  the max/sum-exp2/correction STATISTICS still accumulated in f32, and p
  feeding the MXU in bf16 without the separate f32->bf16 cast.  Targets
  the 39 ms attention VPU chain directly (PERF.md "structural" item 1).
- ``iotafree``: causal band blocks classify visibility with ONE compare of
  a compile-time (BQ, BK) column-minus-row constant against the scalar
  block offset, replacing the two per-element broadcasted_iota builds +
  adds + compare — extends the round-5 causal-split win (which removed
  mask arithmetic from fully-visible blocks) into the band blocks.
- ``parq`` (fwd, resident path): per-q-block lse output blocks instead of
  the revisited whole-sequence lse slice, which lets all three grid dims
  carry "parallel" dimension_semantics.
- ``pipelined`` (fwd): K/V stay in HBM (ANY memory space) and the kernel
  double-buffers block_k-sized chunks VMEM-ward with explicit async
  copies, overlapping the K/V fetch of block i+1 with the softmax chain of
  block i — the streamed forward's copy/compute overlap at sub-grid
  granularity.

All variants have interpret-mode parity tests vs the O(S^2) reference
(tests/test_flash_variants.py).
"""
from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..core.dtype import x64_scope
from .pallas_compat import CompilerParams


def _block_env(name, default):
    """Power-of-two >=128 only: the divisibility-fallback loop in
    flash_attention_bshd halves the block until it divides the sequence, so
    a non-power-of-two would turn supported() shapes into dispatch errors."""
    raw = os.getenv(name)
    if not raw:
        return default
    try:
        v = int(raw)
    except ValueError:
        return default
    if v < 128 or v & (v - 1):
        return default
    return v


DEFAULT_BLOCK_Q = _block_env("PADDLE_TPU_FLASH_BLOCK_Q", 512)
DEFAULT_BLOCK_K = _block_env("PADDLE_TPU_FLASH_BLOCK_K", 512)
_NEG_INF = -1e30
# The streaming softmax runs in BASE 2: folding log2(e) into the logits
# scale turns every exp into the VPU's native exp2 (jnp.exp lowers to
# exp2 + a multiply per element, and the softmax exp over b*h*s^2 logits
# is the kernel's dominant VPU cost).  lse is therefore stored in base-2
# units; the backward consumes it with exp2 as well, and d/d(qk) keeps the
# plain base-e `scale` factor (dS = scale * P * (dP - delta) regardless).
_LOG2E = 1.4426950408889634

_SEQ2 = CompilerParams(
    dimension_semantics=("parallel", "parallel", "arbitrary", "arbitrary"))

#: A/B flag: mask the causal band by multiplying p after exp2 (max over
#: unmasked logits) instead of the -inf select before it
_BAND_MUL = os.getenv("PADDLE_TPU_FLASH_BANDMUL", "0") == "1"

#: variant features understood by the forward / backward kernels
_FWD_FEATURES = frozenset({"bf16chain", "iotafree", "parq", "pipelined"})
_BWD_FEATURES = frozenset({"bf16chain", "iotafree"})


def variant_features(variant, allowed=_FWD_FEATURES):
    """'bf16chain+iotafree' -> frozenset — validated against ``allowed``
    ('base' or '' is the empty set)."""
    if not variant or variant == "base":
        return frozenset()
    feats = frozenset(variant.split("+"))
    bad = feats - allowed
    if bad:
        raise ValueError("unknown flash variant feature(s) %s in %r "
                         "(allowed: %s)" % (sorted(bad), variant,
                                            sorted(allowed)))
    return feats


def canon_variant(feats) -> str:
    return "+".join(sorted(feats)) if feats else "base"


def bwd_variant_of(variant: str) -> str:
    """Strip forward-only features (parq/pipelined) for the backward."""
    return canon_variant(variant_features(variant) & _BWD_FEATURES)


def _sds(shape, dtype, like):
    """ShapeDtypeStruct carrying the vma (varying-manual-axes) of ``like``
    — pallas_call outputs inside a shard_map must declare how they vary
    (the ring-attention inner runs these kernels under manual axes)."""
    try:
        vma = jax.typeof(like).vma
    except Exception:
        vma = None
    if vma:
        try:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
        except TypeError:
            pass
    return jax.ShapeDtypeStruct(shape, dtype)


def _i32(v):
    return jnp.asarray(v, jnp.int32)


def _pid(i):
    # strong int32: program_id is weakly typed and x64 mode would promote
    # its arithmetic to i64, which mosaic cannot lower
    return jax.lax.convert_element_type(pl.program_id(i), jnp.int32)


# VMEM budget for the forward's resident K+V per grid cell
# (s * hg*d * 2 arrays * 2 B bf16, double-buffered by the pipeline);
# sequences whose K/V exceed it take the grid-streamed forward instead.
_RESIDENT_KV_BUDGET = 4 * 1024 * 1024
# VMEM budget for the backward's full-sequence dq accumulator
# (s * hg*d * 4 B f32) — THE sequence-length bound of the Pallas path;
# beyond it the sequence axis must shard (ring attention, SURVEY §5.7).
# 4MB empirically: 8MB of dq scratch plus streamed blocks + dk/dv scratch
# + lse/delta overflowed the 16MB VMEM by 4.5MB at s=8192.
_DQ_SCRATCH_BUDGET = 4 * 1024 * 1024


def _aligned_groups(h: int, d: int):
    out = [hg for hg in (8, 4, 2, 1)
           if h % hg == 0 and (hg * d) % 128 == 0]
    if not out:
        out = [h]  # whole folded axis: legal regardless of alignment
    return out


def _pick_head_group(h: int, d: int, s: int):
    """Heads per grid cell: hg*d must be lane-aligned (%128) and divide h.
    Picks the LARGEST group with hg*d <= 256 — bigger groups amortize grid
    overhead (+0.8k tokens/s measured on the 345M bench; hg*d=512 blew
    VMEM by 156KB at s=1024) — whose backward dq scratch still fits at this
    sequence length (long sequences shrink the group)."""
    def bwd_fits(hg):
        return s * hg * d * 4 <= _DQ_SCRATCH_BUDGET

    forced = _valid_forced_group(h, d)
    if forced is not None:
        return forced
    groups = _aligned_groups(h, d)
    for hg in groups:            # largest first
        if hg * d <= 256 and bwd_fits(hg):
            return hg
    # no group fits the merged backward's full-seq scratch: the SPLIT
    # backward (O(block) VMEM) takes over — pick by block size alone
    for hg in groups:
        if hg * d <= 256:
            return hg
    return groups[-1]


def _kv_fits_resident(s: int, hgd: int) -> bool:
    """K+V bf16, double-buffered — must match _flash_fwd_inner's dispatch
    between the resident and streamed forward."""
    return s * hgd * 2 * 2 <= _RESIDENT_KV_BUDGET


def _valid_forced_group(h: int, d: int):
    raw = os.getenv("PADDLE_TPU_FLASH_HEAD_GROUP")
    if not raw:
        return None
    try:
        hg = int(raw)
    except ValueError:
        return None
    if h % hg == 0 and ((hg * d) % 128 == 0 or hg == h):
        return hg
    return None


def _pick_fwd_head_group(h: int, d: int, s: int, hg_b: int) -> int:
    """The forward has no full-sequence scratch, so it can afford a larger
    group (up to hg*d = 512) when the resident K/V still fits — fewer grid
    cells amortize per-cell overhead.  Falls back to the backward's group.
    A VALID env override (PADDLE_TPU_FLASH_HEAD_GROUP) pins both
    directions; invalid values are ignored in both pickers."""
    if _valid_forced_group(h, d) is not None:
        return hg_b
    for hg in _aligned_groups(h, d):      # largest first
        if hg * d <= 512 and _kv_fits_resident(s, hg * d):
            # the first admissible candidate is always >= hg_b (hg_b
            # satisfies stricter constraints), so no max() needed
            return hg
    return hg_b


#: VMEM allowance for the full-sequence lse+delta blocks the kernels keep
#: resident per grid cell ((1,1,hg,nq,bq) each = hg*s*4 B); the rest of
#: the 16 MB budget is operand blocks + scratch + double buffering
_LSE_RESIDENCY_BUDGET = 8 * 1024 * 1024


def max_supported_seq(h: int, d: int) -> int:
    """Longest sequence the Pallas path supports end-to-end, derived from
    the lse/delta VMEM residency at THIS (h, d)'s head group — a flat cap
    admitted shapes (e.g. d=32 -> hg=8) whose hg*s*4-byte lse blocks fail
    Mosaic allocation at compile time (ADVICE r3).  Beyond the cap the
    sequence axis should shard (ring/Ulysses, SURVEY §5.7)."""
    s = 256 * 1024
    while s >= 1024:
        hg = _pick_head_group(h, d, s)
        if 2 * hg * s * 4 <= _LSE_RESIDENCY_BUDGET:
            return s
        s //= 2
    return 1024


# ---------------------------------------------------------------------------
# shared per-block math (variant-selectable)
# ---------------------------------------------------------------------------

def _band_diff(block_q: int, block_k: int):
    """(BQ, BK) column-minus-row index matrix for the iotafree band mask:
    vis[i, j] = (col0 + j <= row0 + i) = (j - i <= row0 - col0), so a band
    block's whole mask is ONE compare of this (block-independent) matrix
    against the scalar block offset.  Built from in-kernel iotas — Pallas
    under the jax pin rejects captured host constants — but hoisted out of
    the per-k-block loop by the callers (and loop-invariant for Mosaic),
    unlike the base path's per-block row_ids/col_ids builds."""
    return jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) - \
        jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)


def _cell_vis(row0, col0, block_q, block_k, iotafree):
    """Causal visibility mask for the (row0, col0) block (scalars are the
    absolute first row/col of the block)."""
    if iotafree:
        return _band_diff(block_q, block_k) <= (row0 - col0)
    row_ids = row0[None, None] + \
        jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    col_ids = col0[None, None] + \
        jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    return col_ids <= row_ids


def _online_step(q, k, v, m, l, acc, vis, scale, bf16chain, band_mul=False):
    """One streaming-softmax accumulation over a K/V block.

    (m, l, acc) are the running f32 statistics; ``vis`` is None (unmasked
    block) or the (BQ, BK) visibility mask; ``band_mul`` applies vis by
    multiplying p AFTER the exp2 instead of the -inf select before it.
    bf16chain runs the elementwise chain (select, exp2, p) in bf16 with
    f32 statistics — p then feeds the MXU without a separate cast.
    """
    # bf16 x bf16 -> f32 is the MXU's native mode; upcasting operands
    # first quarters matmul throughput
    logits = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * jnp.float32(scale * _LOG2E)
    if bf16chain:
        lb = logits.astype(jnp.bfloat16)
        if vis is not None and not band_mul:
            lb = jnp.where(vis, lb, jnp.bfloat16(_NEG_INF))
        # band_mul: run the max over UNMASKED logits (an over-estimate only
        # shrinks p — lse stays exact) and zero the future columns AFTER
        # the exp2 with one multiply, replacing the -inf select
        new_m = jnp.maximum(m, jnp.max(lb, axis=-1).astype(jnp.float32))
        p = jnp.exp2(lb - new_m.astype(jnp.bfloat16)[:, None])
        if vis is not None and band_mul:
            p = p * vis.astype(jnp.bfloat16)
        psum = jnp.sum(p, axis=-1, dtype=jnp.float32)
    else:
        if vis is not None and not band_mul:
            logits = jnp.where(vis, logits, jnp.float32(_NEG_INF))
        new_m = jnp.maximum(m, jnp.max(logits, axis=-1))
        p = jnp.exp2(logits - new_m[:, None])
        if vis is not None and band_mul:
            p = p * vis.astype(jnp.float32)
        psum = jnp.sum(p, axis=-1)
    correction = jnp.exp2(m - new_m)
    new_l = l * correction + psum
    new_acc = acc * correction[:, None] + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return new_m, new_l, new_acc


def _bwd_head_math(q, k, v, do, lse, delta, vis, scale, bf16chain,
                   want_dq=True, want_dkv=True):
    """The per-head backward block math shared by the merged/dq/dkv
    kernels: recompute p from (q, k, lse), then the requested subset of
    {dv += P^T dO, dk += dS^T Q, dq += dS K}.  Returns a dict of f32 block
    contributions."""
    logits = jnp.float32(scale * _LOG2E) * jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)              # (BQ, BK)
    if bf16chain:
        p = jnp.exp2((logits - lse[:, None]).astype(jnp.bfloat16))
        if vis is not None:
            p = jnp.where(vis, p, jnp.bfloat16(0.0))
    else:
        p = jnp.exp2(logits - lse[:, None])
        if vis is not None:
            p = jnp.where(vis, p, jnp.float32(0.0))
    out = {}
    if want_dkv:
        pc = p.astype(do.dtype)
        # dV += P^T dO
        out["dv"] = jax.lax.dot_general(
            pc, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (BK, D)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)              # (BQ, BK)
    if bf16chain:
        ds = (p * (dp - delta[:, None]).astype(jnp.bfloat16)).astype(q.dtype)
    else:
        ds = (p * (dp - delta[:, None])).astype(q.dtype)
    if want_dkv:
        # dK += dS^T Q
        out["dk"] = jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (BK, D)
    if want_dq:
        # dQ += dS K
        out["dq"] = jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (BQ, D)
    return out


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal, scale, hg,
                d, block_k, bf16chain=False, iotafree=False, parq=False):
    # q/o: (1, BQ, HG*D); k/v: (1, S, HG*D) — the WHOLE sequence resident
    # in VMEM, scanned with a fori loop (measured faster than grid-streamed
    # K/V blocks at these shapes: the pipeline only added grid overhead);
    # lse: (1, 1, HG, NQ, BQ) — or per-q-block (1, 1, HG, 1, BQ) under parq.
    block_q = q_ref.shape[1]
    s = k_ref.shape[1]
    qi = _pid(2)
    row0 = jax.lax.mul(qi, _i32(block_q))

    if causal and not iotafree:
        row_ids = row0[None, None] + \
            jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    if causal and iotafree:
        diff = _band_diff(block_q, block_k)

    for hh in range(hg):
        sl = slice(hh * d, (hh + 1) * d)
        q = q_ref[0, :, sl]                                   # (BQ, D)

        def make_body(masked):
            def body(kb, carry):
                m, l, acc = carry
                start = jax.lax.mul(kb, _i32(block_k))
                k = k_ref[0, pl.ds(start, block_k), sl]
                v = v_ref[0, pl.ds(start, block_k), sl]
                vis = None
                if masked:
                    if iotafree:
                        vis = diff <= (row0 - start)
                    else:
                        col_ids = start[None, None] + \
                            jax.lax.broadcasted_iota(
                                jnp.int32, (block_q, block_k), 1)
                        vis = col_ids <= row_ids
                return _online_step(q, k, v, m, l, acc, vis, scale,
                                    bf16chain,
                                    band_mul=masked and _BAND_MUL)
            return body

        init = (jnp.full((block_q,), jnp.float32(_NEG_INF), jnp.float32),
                jnp.zeros((block_q,), jnp.float32),
                jnp.zeros((block_q, d), jnp.float32))
        if causal:
            # fully-visible blocks skip the mask arithmetic; the diagonal
            # band (block_q // block_k blocks) applies it
            assert block_q % block_k == 0
            ratio = _i32(block_q // block_k)
            num_full = jax.lax.mul(qi, ratio)
            carry = jax.lax.fori_loop(_i32(0), num_full, make_body(False),
                                      init)
            m, l, acc = jax.lax.fori_loop(num_full,
                                          jax.lax.add(num_full, ratio),
                                          make_body(True), carry)
        else:
            m, l, acc = jax.lax.fori_loop(_i32(0), _i32(s // block_k),
                                          make_body(False), init)
        l_safe = jnp.maximum(l, jnp.float32(1e-30))
        o_ref[0, :, sl] = (acc / l_safe[:, None]).astype(o_ref.dtype)
        # lse in base-2 units: m is already log2-scaled
        lse_row = (m + jnp.log(l_safe) * jnp.float32(_LOG2E))[None, :]
        if parq:
            lse_ref[0, 0, hh, pl.ds(0, 1), :] = lse_row
        else:
            lse_ref[0, 0, hh, pl.ds(qi, 1), :] = lse_row


def _fwd_kernel_streamed(q_ref, k_ref, v_ref, o_ref, lse_ref, m_sc, l_sc,
                         acc_sc, *, causal, scale, hg, d, nk,
                         bf16chain=False, iotafree=False):
    # q/o: (1, BQ, HG*D); k/v: (1, BK, HG*D) — ki-th block, streamed by the
    # grid; lse: (1, 1, HG, NQ, BQ); scratch m/l: (HG, BQ) f32,
    # acc: (BQ, HG*D) f32, persistent across the sequential ki iterations.
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]
    qi = _pid(2)
    ki = _pid(3)

    @pl.when(ki == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    def _attend(masked):
        vis = None
        if masked:
            vis = _cell_vis(jax.lax.mul(qi, _i32(block_q)),
                            jax.lax.mul(ki, _i32(block_k)),
                            block_q, block_k, iotafree)
        for hh in range(hg):
            sl = slice(hh * d, (hh + 1) * d)
            q = q_ref[0, :, sl]                               # (BQ, D)
            k = k_ref[0, :, sl]                               # (BK, D)
            v = v_ref[0, :, sl]
            new_m, new_l, new_acc = _online_step(
                q, k, v, m_sc[hh], l_sc[hh], acc_sc[:, sl], vis, scale,
                bf16chain)
            l_sc[hh] = new_l
            acc_sc[:, sl] = new_acc
            m_sc[hh] = new_m

    if causal:
        # split visible blocks into fully-visible (no mask arithmetic —
        # the iota/where VPU work is significant at these shapes) and the
        # diagonal band (masked); the two pl.when branches are disjoint
        first_row = jax.lax.mul(qi, _i32(block_q))
        last_row = first_row + _i32(block_q - 1)
        last_col = jax.lax.mul(ki, _i32(block_k)) + _i32(block_k - 1)
        fully_visible = last_col <= first_row
        diagonal = jnp.logical_and(last_col > first_row,
                                   jax.lax.mul(ki, _i32(block_k)) <=
                                   last_row)

        @pl.when(fully_visible)
        def _compute_full():
            _attend(False)

        @pl.when(diagonal)
        def _compute_diag():
            _attend(True)
    else:
        _attend(False)

    @pl.when(ki == nk - 1)
    def _finalize():
        for hh in range(hg):
            sl = slice(hh * d, (hh + 1) * d)
            l_safe = jnp.maximum(l_sc[hh], jnp.float32(1e-30))
            o_ref[0, :, sl] = (acc_sc[:, sl] /
                               l_safe[:, None]).astype(o_ref.dtype)
            # lse in base-2 units (see _LOG2E)
            lse_ref[0, 0, hh, pl.ds(qi, 1), :] = \
                (m_sc[hh] + jnp.log(l_safe) * jnp.float32(_LOG2E))[None, :]


def _fwd_kernel_pipelined(q_ref, k_any, v_any, o_ref, lse_ref, k_sc, v_sc,
                          sem, *, causal, scale, hg, d, block_k, nk,
                          bf16chain=False, iotafree=False):
    """Forward with EXPLICIT K/V streaming: K/V stay in HBM (ANY memory
    space) and block_k-sized chunks are double-buffered into VMEM scratch
    with async copies, so the fetch of chunk i+1 overlaps the softmax chain
    of chunk i.  Grid (B, n_hg, nq) like the resident kernel; O(block_k)
    K/V VMEM instead of O(S).  Under causal the scan stops after the
    diagonal band; band blocks are classified per-iteration (scalar
    compare), so unlike the resident kernel there is no separate mask-free
    loop — the variant trades that split for the copy overlap."""
    block_q = q_ref.shape[1]
    hgd = hg * d
    bi = _pid(0)
    g = _pid(1)
    qi = _pid(2)
    row0 = jax.lax.mul(qi, _i32(block_q))
    col_base = jax.lax.mul(g, _i32(hgd))

    if causal:
        # only blocks up to the band end attend; rest are strictly future
        assert block_q % block_k == 0
        kend = jax.lax.mul(qi + 1, _i32(block_q // block_k))
    else:
        kend = _i32(nk)

    def kv_dma(slot, kb):
        start = jax.lax.mul(kb, _i32(block_k))
        ck = pltpu.make_async_copy(
            k_any.at[bi, pl.ds(start, block_k), pl.ds(col_base, hgd)],
            k_sc.at[slot], sem.at[slot, 0])
        cv = pltpu.make_async_copy(
            v_any.at[bi, pl.ds(start, block_k), pl.ds(col_base, hgd)],
            v_sc.at[slot], sem.at[slot, 1])
        return ck, cv

    ck0, cv0 = kv_dma(0, _i32(0))
    ck0.start()
    cv0.start()

    def body(kb, carry):
        ms, ls, accs = carry     # per-head tuples: (BQ,), (BQ,), (BQ, D)
        slot = jax.lax.rem(kb, _i32(2))
        nxt = jax.lax.rem(kb + 1, _i32(2))

        @pl.when(kb + 1 < kend)
        def _prefetch():
            ckn, cvn = kv_dma(nxt, kb + 1)
            ckn.start()
            cvn.start()

        ck, cv = kv_dma(slot, kb)
        ck.wait()
        cv.wait()
        start = jax.lax.mul(kb, _i32(block_k))
        vis = None
        if causal:
            # band blocks need the mask; fully-visible ones get vis=True
            # everywhere (the scalar classification is folded into the
            # mask itself — cheaper than a pl.when split inside fori)
            vis = _cell_vis(row0, start, block_q, block_k, iotafree)
        new_ms, new_ls, new_accs = [], [], []
        for hh in range(hg):
            sl = slice(hh * d, (hh + 1) * d)
            nm, nl, na = _online_step(
                q_ref[0, :, sl], k_sc[slot, :, sl], v_sc[slot, :, sl],
                ms[hh], ls[hh], accs[hh], vis, scale, bf16chain)
            new_ms.append(nm)
            new_ls.append(nl)
            new_accs.append(na)
        return tuple(new_ms), tuple(new_ls), tuple(new_accs)

    init = (tuple(jnp.full((block_q,), jnp.float32(_NEG_INF), jnp.float32)
                  for _ in range(hg)),
            tuple(jnp.zeros((block_q,), jnp.float32) for _ in range(hg)),
            tuple(jnp.zeros((block_q, d), jnp.float32)
                  for _ in range(hg)))
    ms, ls, accs = jax.lax.fori_loop(_i32(0), kend, body, init)
    for hh in range(hg):
        sl = slice(hh * d, (hh + 1) * d)
        l_safe = jnp.maximum(ls[hh], jnp.float32(1e-30))
        o_ref[0, :, sl] = (accs[hh] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0, hh, pl.ds(qi, 1), :] = \
            (ms[hh] + jnp.log(l_safe) * jnp.float32(_LOG2E))[None, :]


def _flash_fwd(q3, k3, v3, causal, scale, d, interpret, spec):
    # trace with x64 off: the global x64 mode (needed for paddle's int64
    # semantics) surfaces i64/f64 intermediates that mosaic cannot lower
    with x64_scope(False):
        return _flash_fwd_inner(q3, k3, v3, causal, scale, d, interpret,
                                spec)


def _flash_fwd_inner(q3, k3, v3, causal, scale, d, interpret, spec):
    variant, block_q, block_k, hg = spec
    feats = variant_features(variant, _FWD_FEATURES)
    bf16chain = "bf16chain" in feats
    iotafree = "iotafree" in feats
    b, s, hd = q3.shape
    sk = k3.shape[1]
    n_hg = hd // (hg * d)
    nq = s // block_q
    nk = sk // block_k
    hgd = hg * d
    q_spec3 = pl.BlockSpec((1, block_q, hgd), lambda bi, g, i: (bi, i, g))
    lse_shape = _sds((b, n_hg, hg, nq, block_q), jnp.float32, q3)
    out_shape = _sds((b, s, hd), q3.dtype, q3)
    if "pipelined" in feats:
        # explicit double-buffered K/V DMA — O(block_k) K/V VMEM at ANY
        # sequence length (an alternative to both the resident and the
        # grid-streamed paths; the autotuner decides when it wins)
        kernel = functools.partial(
            _fwd_kernel_pipelined, causal=causal, scale=scale, hg=hg, d=d,
            block_k=block_k, nk=nk, bf16chain=bf16chain, iotafree=iotafree)
        out, lse = pl.pallas_call(
            kernel,
            grid=(b, n_hg, nq),
            in_specs=[q_spec3,
                      pl.BlockSpec(memory_space=pltpu.ANY),
                      pl.BlockSpec(memory_space=pltpu.ANY)],
            out_specs=[
                q_spec3,
                pl.BlockSpec((1, 1, hg, nq, block_q),
                             lambda bi, g, i: (bi, g, 0, 0, 0)),
            ],
            out_shape=[out_shape, lse_shape],
            scratch_shapes=[
                pltpu.VMEM((2, block_k, hgd), k3.dtype),
                pltpu.VMEM((2, block_k, hgd), v3.dtype),
                pltpu.SemaphoreType.DMA((2, 2)),
            ],
            compiler_params=CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=interpret,
        )(q3, k3, v3)
        return out, lse
    if _kv_fits_resident(sk, hgd):
        # fast path: whole K/V resident per cell, fori scan (measured
        # fastest at bench shapes)
        parq = "parq" in feats
        kernel = functools.partial(_fwd_kernel, causal=causal, scale=scale,
                                   hg=hg, d=d, block_k=block_k,
                                   bf16chain=bf16chain, iotafree=iotafree,
                                   parq=parq)
        kv_spec = pl.BlockSpec((1, sk, hgd), lambda bi, g, i: (bi, 0, g))
        if parq:
            # per-q-block lse blocks: nothing is revisited, so every grid
            # dim can carry "parallel" dimension_semantics
            lse_spec = pl.BlockSpec((1, 1, hg, 1, block_q),
                                    lambda bi, g, i: (bi, g, 0, i, 0))
            sem = ("parallel", "parallel", "parallel")
        else:
            # whole folded lse slice per (b, head-group), revisited
            # across the sequential q-block dim
            lse_spec = pl.BlockSpec((1, 1, hg, nq, block_q),
                                    lambda bi, g, i: (bi, g, 0, 0, 0))
            sem = ("parallel", "parallel", "arbitrary")
        out, lse = pl.pallas_call(
            kernel,
            grid=(b, n_hg, nq),
            in_specs=[q_spec3, kv_spec, kv_spec],
            out_specs=[q_spec3, lse_spec],
            out_shape=[out_shape, lse_shape],
            compiler_params=CompilerParams(dimension_semantics=sem),
            interpret=interpret,
        )(q3, k3, v3)
        return out, lse
    # long-sequence path: K/V blocks streamed by the grid — O(block) VMEM,
    # keeps the O(S) capability for sequences whose K/V don't fit resident
    kernel = functools.partial(_fwd_kernel_streamed, causal=causal,
                               scale=scale, hg=hg, d=d, nk=nk,
                               bf16chain=bf16chain, iotafree=iotafree)
    q_spec = pl.BlockSpec((1, block_q, hgd), lambda bi, g, i, j: (bi, i, g))
    kv_spec = pl.BlockSpec((1, block_k, hgd), lambda bi, g, i, j: (bi, j, g))
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, n_hg, nq, nk),
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=[
            q_spec,
            pl.BlockSpec((1, 1, hg, nq, block_q),
                         lambda bi, g, i, j: (bi, g, 0, 0, 0)),
        ],
        out_shape=[out_shape, lse_shape],
        scratch_shapes=[
            pltpu.VMEM((hg, block_q), jnp.float32),
            pltpu.VMEM((hg, block_q), jnp.float32),
            pltpu.VMEM((block_q, hgd), jnp.float32),
        ],
        compiler_params=_SEQ2,
        interpret=interpret,
    )(q3, k3, v3)
    return out, lse


# ---------------------------------------------------------------------------
# backward (merged dQ/dK/dV + split dQ / dKV kernels)
# ---------------------------------------------------------------------------

def _apply_causal_split(compute, causal, qi, ki, block_q, block_k):
    """Run ``compute(masked)`` under the causal block taxonomy: skipped
    (strictly-future), fully-visible (no mask arithmetic), or diagonal
    band (mask applied).  Non-causal runs unconditionally unmasked."""
    if not causal:
        compute(False)
        return
    first_row = jax.lax.mul(qi, _i32(block_q))
    last_row = first_row + _i32(block_q - 1)
    first_col = jax.lax.mul(ki, _i32(block_k))
    last_col = first_col + _i32(block_k - 1)
    fully_visible = last_col <= first_row
    diagonal = jnp.logical_and(last_col > first_row, first_col <= last_row)
    pl.when(fully_visible)(lambda: compute(False))
    pl.when(diagonal)(lambda: compute(True))


def _bwd_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dq_ref, dk_ref, dv_ref, dq_sc, dk_sc, dv_sc, *,
                causal, scale, hg, d, nq, nk, bf16chain=False,
                iotafree=False):
    block_k = k_ref.shape[1]
    block_q = q_ref.shape[1]
    ki = _pid(2)
    qi = _pid(3)

    @pl.when(jnp.logical_and(ki == 0, qi == 0))
    def _init_dq():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    @pl.when(qi == 0)
    def _init_dkv():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    def _compute(masked):
        vis = None
        if masked:
            vis = _cell_vis(jax.lax.mul(qi, _i32(block_q)),
                            jax.lax.mul(ki, _i32(block_k)),
                            block_q, block_k, iotafree)
        row0 = jax.lax.mul(qi, _i32(block_q))
        for hh in range(hg):
            sl = slice(hh * d, (hh + 1) * d)
            g = _bwd_head_math(
                q_ref[0, :, sl], k_ref[0, :, sl], v_ref[0, :, sl],
                do_ref[0, :, sl],
                lse_ref[0, 0, hh, pl.ds(qi, 1), :][0],       # (BQ,) base-2
                delta_ref[0, 0, hh, pl.ds(qi, 1), :][0],     # (BQ,) f32
                vis, scale, bf16chain)
            dv_sc[:, sl] = dv_sc[:, sl] + g["dv"]
            dk_sc[:, sl] = dk_sc[:, sl] + g["dk"]
            # dQ rows qi accumulate in the full-sequence scratch
            dq_sc[pl.ds(row0, block_q), sl] = \
                dq_sc[pl.ds(row0, block_q), sl] + g["dq"]

    # fully-visible blocks skip the iota/where mask arithmetic entirely —
    # only the diagonal band pays it (the same split the streamed forward
    # uses; the two pl.when conditions are disjoint)
    _apply_causal_split(_compute, causal, qi, ki, block_q, block_k)

    @pl.when(qi == nq - 1)
    def _finalize_kv():
        dk_ref[0] = (jnp.float32(scale) * dk_sc[...]).astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[...].astype(dv_ref.dtype)

    @pl.when(jnp.logical_and(ki == nk - 1, qi == nq - 1))
    def _finalize_q():
        dq_ref[0] = (jnp.float32(scale) * dq_sc[...]).astype(dq_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_sc, *, causal, scale, hg, d, nk,
                   bf16chain=False, iotafree=False):
    """dQ-only backward for LONG sequences: grid (b, n_hg, nq, nk) with ki
    innermost, so dq accumulates in a BLOCK-sized scratch (no full-sequence
    scratch — the merged kernel's 16k+ VMEM blocker, PERF.md)."""
    block_k = k_ref.shape[1]
    block_q = q_ref.shape[1]
    qi = _pid(2)
    ki = _pid(3)

    @pl.when(ki == 0)
    def _init():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    def _compute(masked):
        vis = None
        if masked:
            vis = _cell_vis(jax.lax.mul(qi, _i32(block_q)),
                            jax.lax.mul(ki, _i32(block_k)),
                            block_q, block_k, iotafree)
        for hh in range(hg):
            sl = slice(hh * d, (hh + 1) * d)
            g = _bwd_head_math(
                q_ref[0, :, sl], k_ref[0, :, sl], v_ref[0, :, sl],
                do_ref[0, :, sl],
                lse_ref[0, 0, hh, pl.ds(qi, 1), :][0],       # base-2
                delta_ref[0, 0, hh, pl.ds(qi, 1), :][0],
                vis, scale, bf16chain, want_dkv=False)
            dq_sc[:, sl] = dq_sc[:, sl] + g["dq"]

    _apply_causal_split(_compute, causal, qi, ki, block_q, block_k)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = (jnp.float32(scale) * dq_sc[...]).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_sc, dv_sc, *, causal, scale, hg, d,
                    nq, bf16chain=False, iotafree=False):
    """dK/dV backward (ki outer, qi inner) — the merged kernel minus the
    full-sequence dq scratch; pairs with _bwd_dq_kernel for long seqs."""
    block_k = k_ref.shape[1]
    block_q = q_ref.shape[1]
    ki = _pid(2)
    qi = _pid(3)

    @pl.when(qi == 0)
    def _init():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    def _compute(masked):
        vis = None
        if masked:
            vis = _cell_vis(jax.lax.mul(qi, _i32(block_q)),
                            jax.lax.mul(ki, _i32(block_k)),
                            block_q, block_k, iotafree)
        for hh in range(hg):
            sl = slice(hh * d, (hh + 1) * d)
            g = _bwd_head_math(
                q_ref[0, :, sl], k_ref[0, :, sl], v_ref[0, :, sl],
                do_ref[0, :, sl],
                lse_ref[0, 0, hh, pl.ds(qi, 1), :][0],
                delta_ref[0, 0, hh, pl.ds(qi, 1), :][0],
                vis, scale, bf16chain, want_dq=False)
            dv_sc[:, sl] = dv_sc[:, sl] + g["dv"]
            dk_sc[:, sl] = dk_sc[:, sl] + g["dk"]

    _apply_causal_split(_compute, causal, qi, ki, block_q, block_k)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = (jnp.float32(scale) * dk_sc[...]).astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[...].astype(dv_ref.dtype)


def _fold_lse(lse, b, h, hg, block_q):
    """(b, n_hg_f, hg_f, nq_f, bq_f) -> (b, h/hg, hg, s/bq, bq): both the
    head and sequence splits are contiguous, so regrouping between the
    forward's and a backward kernel's (hg, block_q) is a plain reshape."""
    s = lse.shape[3] * lse.shape[4]
    return lse.reshape(b, h // hg, hg, s // block_q, block_q)


def _fold_rows(x, b, h, hg, block_q):
    """(b, s, h) f32 row statistic -> the kernels' (b, n_hg, hg, nq, bq)."""
    s = x.shape[1]
    return jnp.moveaxis(x, -1, 1).reshape(b, h // hg, hg, s // block_q,
                                          block_q)


def _bwd_dq_call(q3, k3, v3, do3, lse, delta, causal, scale, hg, d, spec,
                 interpret):
    """The dq pallas_call of the split backward — also the autotuner's
    flash_bwd_dq runner entry."""
    variant, block_q, block_k = spec
    feats = variant_features(variant, _BWD_FEATURES)
    b, s, hd = q3.shape
    sk = k3.shape[1]
    h = hd // d
    nq = s // block_q
    nk = sk // block_k
    hgd = hg * d
    lse5 = _fold_lse(lse, b, h, hg, block_q)
    delta5 = _fold_rows(delta, b, h, hg, block_q)
    row_spec = pl.BlockSpec((1, 1, hg, nq, block_q),
                            lambda bi, g, i, j: (bi, g, 0, 0, 0))
    q_spec_qout = pl.BlockSpec((1, block_q, hgd),
                               lambda bi, g, i, j: (bi, i, g))
    kv_spec_qout = pl.BlockSpec((1, block_k, hgd),
                                lambda bi, g, i, j: (bi, j, g))
    return pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, scale=scale,
                          hg=hg, d=d, nk=nk,
                          bf16chain="bf16chain" in feats,
                          iotafree="iotafree" in feats),
        grid=(b, h // hg, nq, nk),
        in_specs=[q_spec_qout, kv_spec_qout, kv_spec_qout, q_spec_qout,
                  row_spec, row_spec],
        out_specs=q_spec_qout,
        out_shape=_sds((b, s, hd), q3.dtype, q3),
        scratch_shapes=[pltpu.VMEM((block_q, hgd), jnp.float32)],
        compiler_params=_SEQ2,
        interpret=interpret,
    )(q3, k3, v3, do3, lse5, delta5)


def _bwd_dkv_call(q3, k3, v3, do3, lse, delta, causal, scale, hg, d, spec,
                  interpret):
    """The dk/dv pallas_call of the split backward — also the autotuner's
    flash_bwd_dkv runner entry."""
    variant, block_q, block_k = spec
    feats = variant_features(variant, _BWD_FEATURES)
    b, s, hd = q3.shape
    sk = k3.shape[1]
    h = hd // d
    nq = s // block_q
    nk = sk // block_k
    hgd = hg * d
    lse5 = _fold_lse(lse, b, h, hg, block_q)
    delta5 = _fold_rows(delta, b, h, hg, block_q)
    row_spec = pl.BlockSpec((1, 1, hg, nq, block_q),
                            lambda bi, g, i, j: (bi, g, 0, 0, 0))
    q_spec_kout = pl.BlockSpec((1, block_q, hgd),
                               lambda bi, g, i, j: (bi, j, g))
    kv_spec_kout = pl.BlockSpec((1, block_k, hgd),
                                lambda bi, g, i, j: (bi, i, g))
    return pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, scale=scale,
                          hg=hg, d=d, nq=nq,
                          bf16chain="bf16chain" in feats,
                          iotafree="iotafree" in feats),
        grid=(b, h // hg, nk, nq),
        in_specs=[q_spec_kout, kv_spec_kout, kv_spec_kout, q_spec_kout,
                  row_spec, row_spec],
        out_specs=[kv_spec_kout, kv_spec_kout],
        out_shape=[_sds((b, sk, hd), k3.dtype, k3),
                   _sds((b, sk, hd), v3.dtype, v3)],
        scratch_shapes=[pltpu.VMEM((block_k, hgd), jnp.float32),
                        pltpu.VMEM((block_k, hgd), jnp.float32)],
        compiler_params=_SEQ2,
        interpret=interpret,
    )(q3, k3, v3, do3, lse5, delta5)


def _bwd_merged_call(q3, k3, v3, do3, lse, delta, causal, scale, hg, d,
                     spec, interpret):
    """The merged dQ/dK/dV pallas_call — the autotuner's flash_bwd entry."""
    variant, block_q, block_k = spec
    feats = variant_features(variant, _BWD_FEATURES)
    b, s, hd = q3.shape
    sk = k3.shape[1]
    h = hd // d
    nq = s // block_q
    nk = sk // block_k
    hgd = hg * d
    lse5 = _fold_lse(lse, b, h, hg, block_q)
    delta5 = _fold_rows(delta, b, h, hg, block_q)
    q_spec = pl.BlockSpec((1, block_q, hgd), lambda bi, g, i, j: (bi, j, g))
    kv_spec = pl.BlockSpec((1, block_k, hgd), lambda bi, g, i, j: (bi, i, g))
    row_spec = pl.BlockSpec((1, 1, hg, nq, block_q),
                            lambda bi, g, i, j: (bi, g, 0, 0, 0))
    return pl.pallas_call(
        functools.partial(_bwd_kernel, causal=causal, scale=scale,
                          hg=hg, d=d, nq=nq, nk=nk,
                          bf16chain="bf16chain" in feats,
                          iotafree="iotafree" in feats),
        grid=(b, h // hg, nk, nq),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=[
            # dq: whole-sequence block, revisited; written at the last step
            pl.BlockSpec((1, s, hgd), lambda bi, g, i, j: (bi, 0, g)),
            kv_spec,
            kv_spec,
        ],
        out_shape=[
            _sds((b, s, hd), q3.dtype, q3),
            _sds((b, sk, hd), k3.dtype, k3),
            _sds((b, sk, hd), v3.dtype, v3),
        ],
        scratch_shapes=[
            pltpu.VMEM((s, hgd), jnp.float32),
            pltpu.VMEM((block_k, hgd), jnp.float32),
            pltpu.VMEM((block_k, hgd), jnp.float32),
        ],
        compiler_params=_SEQ2,
        interpret=interpret,
    )(q3, k3, v3, do3, lse5, delta5)


def _flash_bwd(q3, k3, v3, o3, lse, do3, causal, scale, d, interpret, spec,
               dlse=None):
    # dlse: optional (b, s, h) f32 cotangent of a base-e lse OUTPUT
    # (flash_attention_bshd_with_lse): it folds into the kernels as
    # delta - dlse — dS_ij = P_ij (dP_ij - delta_i + dlse_i), so the
    # existing kernels run unchanged.
    # spec: ("merged", variant, block_q, block_k, hg) or
    #       ("split", (variant, bq, bk), (variant, bq, bk), hg) — decided
    # by the wrapper (default: merged while the full-seq dq scratch fits).
    with x64_scope(False):
        b, s, hd = q3.shape
        h = hd // d
        # delta = rowsum(dO * O) per head — cheap, fused by XLA; folded to
        # the kernels' (b, n_hg, hg, nq, bq) row layout per call
        delta = jnp.sum(
            do3.reshape(b, s, h, d).astype(jnp.float32) *
            o3.reshape(b, s, h, d).astype(jnp.float32), axis=-1)  # (b,s,h)
        if dlse is not None:
            delta = delta - dlse.astype(jnp.float32)
        if spec[0] == "split":
            _, dq_spec, dkv_spec, hg = spec
            dq = _bwd_dq_call(q3, k3, v3, do3, lse, delta, causal, scale,
                              hg, d, dq_spec, interpret)
            dk, dv = _bwd_dkv_call(q3, k3, v3, do3, lse, delta, causal,
                                   scale, hg, d, dkv_spec, interpret)
            return dq, dk, dv
        _, variant, block_q, block_k, hg = spec
        return _bwd_merged_call(q3, k3, v3, do3, lse, delta, causal, scale,
                                hg, d, (variant, block_q, block_k),
                                interpret)


# ---------------------------------------------------------------------------
# reference + custom_vjp wiring
# ---------------------------------------------------------------------------

def _reference_bhsd(q, k, v, causal, scale):
    logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, _NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q3, k3, v3, causal, scale, d, interpret, fwd_spec, bwd_spec):
    # fwd_spec: (variant, block_q, block_k, hg) — the forward and backward
    # tune independently (the backward's full-sequence dq scratch binds its
    # head group; the forward can amortize more heads per grid cell)
    out, _ = _flash_fwd(q3, k3, v3, causal, scale, d, interpret, fwd_spec)
    return out


def _flash_vjp_fwd(q3, k3, v3, causal, scale, d, interpret, fwd_spec,
                   bwd_spec):
    out, lse = _flash_fwd(q3, k3, v3, causal, scale, d, interpret, fwd_spec)
    return out, (q3, k3, v3, out, lse)


def _flash_vjp_bwd(causal, scale, d, interpret, fwd_spec, bwd_spec, res, g):
    q3, k3, v3, out, lse = res
    # the backward regroups the folded lse rows itself (plain reshape —
    # both the head and q-block splits are contiguous)
    return _flash_bwd(q3, k3, v3, out, lse, g, causal, scale, d, interpret,
                      bwd_spec)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _prep_blocks(s, sk, causal, block_q, block_k, what):
    """Shared block policy of the public BSHD wrappers: shrink to the
    largest divisible power-of-two blocks (>=128), cap block_k at block_q
    under causal (the band split needs block_q %% block_k == 0), and raise
    on ragged tails."""
    block_q = min(block_q, s)
    block_k = min(block_k, sk)
    while block_q > 128 and s % block_q:
        block_q //= 2
    while block_k > 128 and sk % block_k:
        block_k //= 2
    if causal and block_k > block_q:
        block_k = block_q
    if s % block_q or sk % block_k:
        raise ValueError(
            "%s: seq lengths (%d, %d) must be divisible by block sizes "
            "(%d, %d) — ragged tails would be silently dropped; use the "
            "XLA path (kernels.flash_attention.supported() gates this)"
            % (what, s, sk, block_q, block_k))
    return block_q, block_k


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_lse(q3, k3, v3, causal, scale, d, interpret, fwd_spec, bwd_spec):
    out, lse2 = _flash_fwd(q3, k3, v3, causal, scale, d, interpret,
                           fwd_spec)
    return out, lse2


def _flash_lse_vjp_fwd(q3, k3, v3, causal, scale, d, interpret, fwd_spec,
                       bwd_spec):
    out, lse2 = _flash_fwd(q3, k3, v3, causal, scale, d, interpret,
                           fwd_spec)
    return (out, lse2), (q3, k3, v3, out, lse2)


def _flash_lse_vjp_bwd(causal, scale, d, interpret, fwd_spec, bwd_spec,
                       res, g):
    q3, k3, v3, out, lse2 = res
    dout, dlse2 = g
    b, s, hd = q3.shape
    h = hd // d
    # unfold the (b, n_hg, hg, nq, bq) base-2 lse cotangent to (b, s, h)
    # base-e: lse2 = lse_e * log2e, so dlse_e = dlse2 * log2e
    dlse = jnp.moveaxis(
        dlse2.reshape(b, h, s), 1, -1) * jnp.float32(_LOG2E)
    return _flash_bwd(q3, k3, v3, out, lse2, dout, causal, scale, d,
                      interpret, bwd_spec, dlse=dlse)


_flash_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


# ---------------------------------------------------------------------------
# autotune wiring: keys, spec resolution, candidates, runners
# ---------------------------------------------------------------------------

def autotune_key(b, s, sk, h, d, dtype, causal):
    from . import autotune as at
    return {"b": int(b), "s": int(s), "sk": int(sk), "h": int(h),
            "d": int(d), "dtype": str(jnp.dtype(dtype)),
            "causal": bool(causal), "platform": at.platform()}


def _valid_blocks(bq, bk, s, sk, causal):
    if not (isinstance(bq, int) and isinstance(bk, int)):
        return False
    if bq < 128 or bk < 128 or s % bq or sk % bk:
        return False
    if causal and (bk > bq or bq % bk):
        return False
    return True


def _valid_hg(hg, h, d):
    return isinstance(hg, int) and hg >= 1 and h % hg == 0 and \
        ((hg * d) % 128 == 0 or hg == h)


def _sane_fwd_spec(cand, s, sk, h, d, causal, default):
    """Validate a resolved/pinned flash_fwd candidate against the kernel's
    divisibility and alignment constraints; anything off falls back to the
    hand-tuned default (cache entries and pins are user input)."""
    cfg = cand.get("config", {})
    bq, bk, hg = cfg.get("block_q"), cfg.get("block_k"), cfg.get("hg")
    try:
        variant_features(cand.get("variant", "base"), _FWD_FEATURES)
    except ValueError:
        return ("base",) + default
    if not (_valid_blocks(bq, bk, s, sk, causal) and _valid_hg(hg, h, d)):
        return ("base",) + default
    return (cand["variant"], bq, bk, hg)


def _sane_bwd_blocks(cand, s, sk, causal, default):
    cfg = cand.get("config", {})
    bq, bk = cfg.get("block_q"), cfg.get("block_k")
    try:
        variant_features(cand.get("variant", "base"), _BWD_FEATURES)
    except ValueError:
        return ("base",) + default
    if not _valid_blocks(bq, bk, s, sk, causal):
        return ("base",) + default
    return (cand["variant"], bq, bk)


def _sane_bwd_merged(cand, s, sk, h, d, causal, default):
    cfg = cand.get("config", {})
    hg = cfg.get("hg")
    variant, bq, bk = _sane_bwd_blocks(cand, s, sk, causal, default[:2])
    if not _valid_hg(hg, h, d) or \
            max(s, sk) * hg * d * 4 > _DQ_SCRATCH_BUDGET:
        return ("merged", "base") + default
    return ("merged", variant, bq, bk, hg)


def _resolve_specs(b, s, sk, h, d, dtype, causal, block_q, block_k, hg_f,
                   hg_b, variant=None, tie_groups=False,
                   use_autotune=True):
    """(fwd_spec, bwd_spec) for one call: an explicit ``variant`` or
    caller-pinned block sizes (``use_autotune=False``) bypass the autotuner
    entirely (the A/B and parity-test entry); otherwise the specs resolve
    through autotune.resolve() with the hand-tuned values as the registered
    defaults — identical programs until tuning runs."""
    split = max(s, sk) * hg_b * d * 4 > _DQ_SCRATCH_BUDGET
    if variant is not None or not use_autotune:
        variant = variant or "base"
        fv = canon_variant(variant_features(variant, _FWD_FEATURES))
        bv = bwd_variant_of(variant)
        fwd_spec = (fv, block_q, block_k, hg_f)
        bwd_spec = (("split", (bv, block_q, block_k),
                     (bv, block_q, block_k), hg_b) if split
                    else ("merged", bv, block_q, block_k, hg_b))
        return fwd_spec, bwd_spec
    from . import autotune as at
    key = autotune_key(b, s, sk, h, d, dtype, causal)
    fwd_spec = _sane_fwd_spec(at.resolve("flash_fwd", key), s, sk, h, d,
                              causal, (block_q, block_k, hg_f))
    if split:
        bwd_spec = ("split",
                    _sane_bwd_blocks(at.resolve("flash_bwd_dq", key),
                                     s, sk, causal, (block_q, block_k)),
                    _sane_bwd_blocks(at.resolve("flash_bwd_dkv", key),
                                     s, sk, causal, (block_q, block_k)),
                    hg_b)
    else:
        bwd_spec = _sane_bwd_merged(at.resolve("flash_bwd", key),
                                    s, sk, h, d, causal,
                                    (block_q, block_k, hg_b))
    if tie_groups:
        # one group for both directions: the lse OUTPUT layout must match
        # what the caller-visible (b, s, h) unfold assumes alongside the
        # backward's consumption (flash_attention_bshd_with_lse).  A tuned
        # fwd winner with a DIFFERENT head group is discarded for the
        # hand-tuned default rather than silently re-grouped — the
        # (variant, blocks, hg) combination after a re-group was never
        # timed, and alternate-hg candidates differ ONLY by hg.
        hg = bwd_spec[4] if bwd_spec[0] == "merged" else bwd_spec[3]
        if fwd_spec[3] != hg:
            fwd_spec = ("base", block_q, block_k, hg)
    return fwd_spec, bwd_spec


_CAND_FWD_VARIANTS = ("iotafree", "bf16chain", "bf16chain+iotafree")
_CAND_FWD_RESIDENT = ("parq", "iotafree+parq")
_CAND_FWD_PIPELINED = ("pipelined", "iotafree+pipelined")
_CAND_BWD_VARIANTS = ("iotafree", "bf16chain", "bf16chain+iotafree")


def _candidate_blocks(s, sk, causal, bq0, bk0):
    pairs = [(bq0, bk0)]
    for bq in (256, 512, 1024):
        for bk in (128, 256, 512):
            if bq > s or bk > sk or s % bq or sk % bk:
                continue
            if causal and (bk > bq or bq % bk):
                continue
            if (bq, bk) not in pairs:
                pairs.append((bq, bk))
    return pairs[:6]


def _default_cfg(key):
    s, sk, h, d, causal = (key[k] for k in ("s", "sk", "h", "d", "causal"))
    hg_b = _pick_head_group(h, d, max(s, sk))
    hg_f = _pick_fwd_head_group(h, d, max(s, sk), hg_b)
    bq0, bk0 = _prep_blocks(s, sk, causal, DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K,
                            "autotune")
    return bq0, bk0, hg_f, hg_b


def _fwd_candidates(key):
    s, sk, h, d, causal = (key[k] for k in ("s", "sk", "h", "d", "causal"))
    bq0, bk0, hg_f, hg_b = _default_cfg(key)
    cands = [{"variant": "base",
              "config": {"block_q": bq0, "block_k": bk0, "hg": hg_f}}]
    variants = list(_CAND_FWD_VARIANTS) + list(_CAND_FWD_PIPELINED)
    if _kv_fits_resident(sk, hg_f * d):
        variants += list(_CAND_FWD_RESIDENT)
    for bq, bk in _candidate_blocks(s, sk, causal, bq0, bk0):
        for v in (["base"] if (bq, bk) != (bq0, bk0) else []) + variants:
            cand = {"variant": v,
                    "config": {"block_q": bq, "block_k": bk, "hg": hg_f}}
            if cand not in cands:
                cands.append(cand)
    # alternate head groups for the base variant only (bounds the grid)
    for hg in _aligned_groups(h, d):
        if hg != hg_f and hg * d <= 512:
            cands.append({"variant": "base",
                          "config": {"block_q": bq0, "block_k": bk0,
                                     "hg": hg}})
    return cands


def _bwd_candidates_merged(key):
    s, sk, h, d, causal = (key[k] for k in ("s", "sk", "h", "d", "causal"))
    bq0, bk0, hg_f, hg_b = _default_cfg(key)
    cands = [{"variant": "base",
              "config": {"block_q": bq0, "block_k": bk0, "hg": hg_b}}]
    for bq, bk in _candidate_blocks(s, sk, causal, bq0, bk0):
        for v in (["base"] if (bq, bk) != (bq0, bk0) else []) + \
                list(_CAND_BWD_VARIANTS):
            cand = {"variant": v,
                    "config": {"block_q": bq, "block_k": bk, "hg": hg_b}}
            if cand not in cands:
                cands.append(cand)
    for hg in _aligned_groups(h, d):
        if hg != hg_b and hg * d <= 256 and \
                max(s, sk) * hg * d * 4 <= _DQ_SCRATCH_BUDGET:
            cands.append({"variant": "base",
                          "config": {"block_q": bq0, "block_k": bk0,
                                     "hg": hg}})
    return cands


def _bwd_candidates_split(key):
    s, sk, causal = key["s"], key["sk"], key["causal"]
    bq0, bk0, _, _ = _default_cfg(key)
    cands = [{"variant": "base", "config": {"block_q": bq0,
                                            "block_k": bk0}}]
    for bq, bk in _candidate_blocks(s, sk, causal, bq0, bk0):
        for v in (["base"] if (bq, bk) != (bq0, bk0) else []) + \
                list(_CAND_BWD_VARIANTS):
            cand = {"variant": v, "config": {"block_q": bq, "block_k": bk}}
            if cand not in cands:
                cands.append(cand)
    return cands


#: per-key synthetic operand cache shared by the runner factories (the
#: backward runners also reuse the default-forward (out, lse) residuals)
_RUNNER_DATA: dict = {}


def _runner_data(key):
    from . import autotune as at
    ks = at.key_str(key)
    hit = _RUNNER_DATA.get(ks)
    if hit is not None:
        return hit
    b, s, sk, h, d = (key[k] for k in ("b", "s", "sk", "h", "d"))
    causal = key["causal"]
    dtype = jnp.dtype(key["dtype"])
    interpret = key["platform"] != "tpu"
    rng = np.random.RandomState(0)
    with x64_scope(False):
        q3 = jnp.asarray(rng.standard_normal((b, s, h * d)), dtype)
        k3 = jnp.asarray(rng.standard_normal((b, sk, h * d)), dtype)
        v3 = jnp.asarray(rng.standard_normal((b, sk, h * d)), dtype)
        do3 = jnp.asarray(rng.standard_normal((b, s, h * d)), dtype)
        bq0, bk0, hg_f, hg_b = _default_cfg(key)
        scale = 1.0 / d ** 0.5
        out, lse = jax.jit(lambda a, bb, c: _flash_fwd(
            a, bb, c, causal, scale, d, interpret,
            ("base", bq0, bk0, hg_b)))(q3, k3, v3)
        delta = jnp.sum(
            do3.reshape(b, s, h, d).astype(jnp.float32) *
            out.reshape(b, s, h, d).astype(jnp.float32), axis=-1)
        jax.block_until_ready((out, lse, delta))
    data = {"q3": q3, "k3": k3, "v3": v3, "do3": do3, "out": out,
            "lse": lse, "delta": delta, "scale": scale, "hg_b": hg_b,
            "interpret": interpret}
    _RUNNER_DATA[ks] = data
    return data


def _fwd_runner(cand, key):
    data = _runner_data(key)
    cfg = cand["config"]
    spec = (cand["variant"], cfg["block_q"], cfg["block_k"], cfg["hg"])
    causal, d = key["causal"], key["d"]
    fn = jax.jit(lambda q, k, v: _flash_fwd(
        q, k, v, causal, data["scale"], d, data["interpret"], spec))

    def run():
        jax.block_until_ready(fn(data["q3"], data["k3"], data["v3"]))
    return run


def _bwd_runner(which):
    def make(cand, key):
        data = _runner_data(key)
        cfg = cand["config"]
        causal, d = key["causal"], key["d"]
        hg = cfg.get("hg", data["hg_b"])
        spec = (cand["variant"], cfg["block_q"], cfg["block_k"])
        call = {"merged": _bwd_merged_call, "dq": _bwd_dq_call,
                "dkv": _bwd_dkv_call}[which]

        def timed(q, k, v, do, lse, delta):
            # same x64-off trace scope as the production entry
            # (_flash_bwd) — under the global x64 mode the candidate
            # would otherwise lower a different (or unlowerable) program
            # than the one production runs
            with x64_scope(False):
                return call(q, k, v, do, lse, delta, causal,
                            data["scale"], hg, d, spec,
                            data["interpret"])
        fn = jax.jit(timed)

        def run():
            jax.block_until_ready(fn(
                data["q3"], data["k3"], data["v3"], data["do3"],
                data["lse"], data["delta"]))
        return run
    return make


def _runner_cleanup(key):
    from . import autotune as at
    _RUNNER_DATA.pop(at.key_str(key), None)


# -- abstract traceables (TPU504 / trace-tier audit) -------------------------
# Data-free builders of each candidate's program: args are
# ShapeDtypeStructs, so make_jaxpr prices the BlockSpec working set
# without touching a device — the autotuner's pre-compile VMEM gate and
# the analysis registry's per-variant kernel programs both come from
# these.

def _fwd_traceable(cand, key):
    b, s, sk, h, d = (key[k] for k in ("b", "s", "sk", "h", "d"))
    causal, dtype = key["causal"], jnp.dtype(key["dtype"])
    cfg = cand["config"]
    spec = (cand["variant"], cfg["block_q"], cfg["block_k"], cfg["hg"])
    scale = 1.0 / d ** 0.5

    def fn(q, k, v):
        return _flash_fwd(q, k, v, causal, scale, d, True, spec)
    sds = jax.ShapeDtypeStruct
    return fn, (sds((b, s, h * d), dtype), sds((b, sk, h * d), dtype),
                sds((b, sk, h * d), dtype))


def _bwd_traceable(which):
    def make(cand, key):
        b, s, sk, h, d = (key[k] for k in ("b", "s", "sk", "h", "d"))
        causal, dtype = key["causal"], jnp.dtype(key["dtype"])
        cfg = cand["config"]
        bq0, _bk0, _hg_f, hg_b = _default_cfg(key)
        hg = cfg.get("hg", hg_b)
        spec = (cand["variant"], cfg["block_q"], cfg["block_k"])
        scale = 1.0 / d ** 0.5
        call = {"merged": _bwd_merged_call, "dq": _bwd_dq_call,
                "dkv": _bwd_dkv_call}[which]

        def fn(q, k, v, do, lse, delta):
            with x64_scope(False):
                return call(q, k, v, do, lse, delta, causal, scale, hg, d,
                            spec, True)
        sds = jax.ShapeDtypeStruct
        # lse/delta in the layout the default forward produces (what the
        # production bwd — and the timed runner — actually receives)
        return fn, (sds((b, s, h * d), dtype), sds((b, sk, h * d), dtype),
                    sds((b, sk, h * d), dtype), sds((b, s, h * d), dtype),
                    sds((b, h // hg_b, hg_b, s // bq0, bq0), jnp.float32),
                    sds((b, s, h), jnp.float32))
    return make


def _register_families():
    from . import autotune as at
    at.register_family("flash_fwd", _fwd_candidates, _fwd_runner,
                       cleanup=_runner_cleanup, traceable=_fwd_traceable)
    at.register_family("flash_bwd", _bwd_candidates_merged,
                       _bwd_runner("merged"), cleanup=_runner_cleanup,
                       traceable=_bwd_traceable("merged"))
    at.register_family("flash_bwd_dq", _bwd_candidates_split,
                       _bwd_runner("dq"), cleanup=_runner_cleanup,
                       traceable=_bwd_traceable("dq"))
    at.register_family("flash_bwd_dkv", _bwd_candidates_split,
                       _bwd_runner("dkv"), cleanup=_runner_cleanup,
                       traceable=_bwd_traceable("dkv"))


_register_families()


# ---------------------------------------------------------------------------
# public BSHD wrappers
# ---------------------------------------------------------------------------

def flash_attention_bshd_with_lse(q, k, v, causal=False, scale=None,
                                  block_q=DEFAULT_BLOCK_Q,
                                  block_k=DEFAULT_BLOCK_K,
                                  interpret=False, variant=None):
    """Like :func:`flash_attention_bshd_native` but ALSO returns the
    row logsumexp in BASE E, shape (B, S, H) — and stays differentiable
    when the caller consumes both (the lse cotangent folds into the
    backward kernels as ``delta - dlse``).  This is the building block
    the ring-attention inner needs (r4 verdict #3): per-shard
    (out, lse) pairs combine exactly like global attention."""
    b, s, h, d = q.shape
    sk = k.shape[1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    hg_b = _pick_head_group(h, d, max(s, sk))
    default_blocks = (block_q, block_k) == (DEFAULT_BLOCK_Q,
                                            DEFAULT_BLOCK_K)
    block_q, block_k = _prep_blocks(s, sk, causal, block_q, block_k,
                                    "flash_attention_with_lse")
    fwd_spec, bwd_spec = _resolve_specs(
        b, s, sk, h, d, q.dtype, causal, block_q, block_k, hg_b, hg_b,
        variant=variant, tie_groups=True, use_autotune=default_blocks)
    q3 = q.reshape(b, s, h * d)
    k3 = k.reshape(b, sk, h * d)
    v3 = v.reshape(b, sk, h * d)
    out, lse2 = _flash_lse(q3, k3, v3, causal, float(scale), d, interpret,
                           fwd_spec, bwd_spec)
    # (b, n_hg, hg, nq, bq) base-2 -> (b, s, h) base-e
    lse = jnp.moveaxis(lse2.reshape(b, h, s), 1, -1) / jnp.float32(_LOG2E)
    return out.reshape(b, s, h, d), lse


def flash_attention_bshd_native(q, k, v, causal=False, scale=None,
                                block_q=DEFAULT_BLOCK_Q,
                                block_k=DEFAULT_BLOCK_K, interpret=False,
                                variant=None):
    """q,k,v: (B, S, H, D) — the model's native layout; no transposes.
    ``variant`` pins a kernel variant (e.g. "bf16chain+iotafree") for both
    directions, bypassing the autotuner; None resolves through it."""
    b, s, h, d = q.shape
    sk = k.shape[1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    hg_b = _pick_head_group(h, d, max(s, sk))
    hg_f = _pick_fwd_head_group(h, d, max(s, sk), hg_b)
    default_blocks = (block_q, block_k) == (DEFAULT_BLOCK_Q,
                                            DEFAULT_BLOCK_K)
    block_q, block_k = _prep_blocks(s, sk, causal, block_q, block_k,
                                    "flash_attention")
    fwd_spec, bwd_spec = _resolve_specs(
        b, s, sk, h, d, q.dtype, causal, block_q, block_k, hg_f, hg_b,
        variant=variant, use_autotune=default_blocks)
    q3 = q.reshape(b, s, h * d)
    k3 = k.reshape(b, sk, h * d)
    v3 = v.reshape(b, sk, h * d)
    out = _flash(q3, k3, v3, causal, float(scale), d, interpret, fwd_spec,
                 bwd_spec)
    return out.reshape(b, s, h, d)


def flash_attention_bhsd(q, k, v, causal=False, scale=None,
                         block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                         interpret=False, variant=None):
    """q,k,v: (B, H, S, D) — compat wrapper over the native BSHD kernel
    (introduces two transposes; the model path uses BSHD directly)."""
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out = flash_attention_bshd_native(qt, kt, vt, causal=causal, scale=scale,
                                      block_q=block_q, block_k=block_k,
                                      interpret=interpret, variant=variant)
    return jnp.swapaxes(out, 1, 2)
