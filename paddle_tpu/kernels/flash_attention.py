"""Flash attention (Pallas TPU).

Blockwise-softmax attention with O(S) memory — the capability the reference
lacks entirely (SURVEY.md §5.7: no flash/ring attention in the snapshot; its
fused FMHA paddle/fluid/operators/fused/fmha_ref.h is still O(S^2)).

Forward and backward are dedicated Pallas kernels (FlashAttention-2 style
custom_vjp; see flash_attention_pallas.py).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_DEFAULT_BLOCK_Q = 128
_DEFAULT_BLOCK_K = 128


def _platform() -> str:
    try:
        return jax.default_backend()
    except Exception:
        return "cpu"


def supported(q, k=None) -> bool:
    """Whether the Pallas path applies to (B, S, H, D) query/key.

    Restricted to square self-attention (s_q == s_k, both block-aligned):
    the kernel's causal mask is start-aligned and a ragged key tail would be
    silently dropped — cross/cached attention takes the XLA reference path.
    """
    import os
    if os.getenv("PADDLE_TPU_DISABLE_FLASH", "").lower() in ("1", "true",
                                                             "yes"):
        return False
    if _platform() != "tpu":
        return False
    if q.ndim != 4:
        return False
    s, h, d = q.shape[1], q.shape[2], q.shape[3]
    if k is not None and k.shape[1] != s:
        return False
    if s % _DEFAULT_BLOCK_Q or d not in (64, 128, 256):
        return False
    # the forward holds K+V VMEM-resident; very long sequences exceed the
    # budget and must take the XLA path
    from .flash_attention_pallas import max_supported_seq
    return s <= max_supported_seq(h, d)


def flash_attention_bshd(q, k, v, causal=False, scale=None):
    """q,k,v: (B, S, H, D) -> (B, S, H, D) — native layout, no transposes."""
    from .flash_attention_pallas import flash_attention_bshd_native
    return flash_attention_bshd_native(q, k, v, causal=causal, scale=scale)


def flash_attention_bshd_with_lse(q, k, v, causal=False, scale=None,
                                  interpret=False):
    """(out, lse): lse is the base-e row logsumexp, (B, S, H) — the
    differentiable building block of the ring-attention inner."""
    from .flash_attention_pallas import \
        flash_attention_bshd_with_lse as _impl
    return _impl(q, k, v, causal=causal, scale=scale, interpret=interpret)
