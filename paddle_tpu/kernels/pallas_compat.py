"""Pallas API drift shims shared by the kernel modules.

The kernels are written against the current Pallas surface; the CI/test
environment pins jax 0.4.37 (see .github/workflows/ci.yml), where
``pltpu.CompilerParams`` is still spelled ``TPUCompilerParams``.  Resolving
the name here keeps every kernel importable (and interpret-mode testable)
on both — this single missing attribute used to fail COLLECTION of the
whole kernel test set under the pin.
"""
from __future__ import annotations

from jax.experimental.pallas import tpu as pltpu

CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    getattr(pltpu, "TPUCompilerParams")

__all__ = ["CompilerParams"]
