"""Pallas TPU LayerNorm + row-softmax kernels (SURVEY.md §7 stage 3 hot set;
reference CUDA: paddle/phi/kernels/gpu/layer_norm_kernel.cu,
fused_layernorm_residual_dropout_bias.h; softmax_kernel.cu).

Design: rows (all leading dims flattened) are tiled over a 1-D grid; each
grid step loads a (BLOCK_ROWS, F) tile into VMEM, computes f32 statistics on
the VPU, and writes the normalized tile back in the input dtype.  The
backward kernels recompute x_hat from the saved (mean, rstd) row statistics
— O(F) memory per row, matching the fused CUDA kernels' design.

NOTE on dispatch: XLA already fuses layer-norm/softmax chains to ~peak on
TPU (measured — PERF.md), so the framework defaults to the XLA path; these
kernels are selected via FLAGS_use_pallas_norm=1 and exist as the
hand-kernel escape hatch (and the pattern template for custom fusions via
utils.cpp_extension.register_op).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..core.dtype import x64_scope
from jax.experimental.pallas import tpu as pltpu  # noqa: F401
from .pallas_compat import CompilerParams

DEFAULT_BLOCK_ROWS = 256


def _shrink_rows(block_rows, n):
    """The hand-tuned row-block policy: shrink the default to the largest
    power-of-two divisor of n (floor 8)."""
    br = min(block_rows, n)
    while br > 8 and n % br:
        br //= 2
    return br


def autotune_key(n, f, dtype):
    from . import autotune as at
    return {"n": int(n), "f": int(f), "dtype": str(jnp.dtype(dtype)),
            "platform": at.platform()}


def _ln_candidates(key):
    """ln autotune family: the row-block size of the LayerNorm grid.
    Candidate [0] is the hand-tuned _shrink_rows default."""
    n = key["n"]
    br0 = _shrink_rows(DEFAULT_BLOCK_ROWS, n)
    cands = [{"variant": "base", "config": {"block_rows": br0}}]
    for br in (1024, 512, 256, 128, 64, 32, 16, 8):
        if br != br0 and br <= n and n % br == 0:
            cands.append({"variant": "base", "config": {"block_rows": br}})
    return cands


#: per-key synthetic operands shared across one tune() run's candidates
#: (see ce_pallas._LSE_RUNNER_DATA); freed by the cleanup hook
_LN_RUNNER_DATA: dict = {}


def _ln_runner(cand, key):
    import numpy as np
    from . import autotune as at
    n, f = key["n"], key["f"]
    dtype = jnp.dtype(key["dtype"])
    interpret = key["platform"] != "tpu"
    br = cand["config"]["block_rows"]
    ks = at.key_str(key)
    data = _LN_RUNNER_DATA.get(ks)
    if data is None:
        rng = np.random.RandomState(0)
        data = (jnp.asarray(rng.standard_normal((n, f)), dtype),
                jnp.ones((f,), dtype), jnp.zeros((f,), dtype))
        _LN_RUNNER_DATA[ks] = data
    x2, gamma, beta = data

    def timed(x, g, b):
        # same x64-off trace scope as the production entry (_ln_core)
        with x64_scope(False):
            return _ln_fwd(x, g, b, 1e-5, br, interpret)
    fn = jax.jit(timed)

    def run():
        jax.block_until_ready(fn(x2, gamma, beta))
    return run


def _ln_runner_cleanup(key):
    from . import autotune as at
    _LN_RUNNER_DATA.pop(at.key_str(key), None)


def _ln_resolve_rows(n, f, dtype, block_rows):
    """Row-block pick for one call: explicit non-default block_rows is
    honored as-is; the default resolves through the autotuner (returning
    the hand-tuned shrink unless a tuned/pinned config exists)."""
    if block_rows != DEFAULT_BLOCK_ROWS:
        return _shrink_rows(block_rows, n)
    from . import autotune as at
    cand = at.resolve("ln", autotune_key(n, f, dtype))
    br = cand.get("config", {}).get("block_rows")
    if isinstance(br, int) and 8 <= br <= n and n % br == 0:
        return br
    return _shrink_rows(block_rows, n)


def _supported_feature_dim(f: int) -> bool:
    return f % 128 == 0


# ---------------------------------------------------------------------------
# LayerNorm
# ---------------------------------------------------------------------------

def _ln_fwd_kernel(x_ref, g_ref, b_ref, o_ref, mean_ref, rstd_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)              # (R, F)
    mean = jnp.mean(x, axis=-1)
    var = jnp.mean(jnp.square(x), axis=-1) - jnp.square(mean)
    rstd = jax.lax.rsqrt(var + jnp.float32(eps))
    xhat = (x - mean[:, None]) * rstd[:, None]
    o_ref[...] = (xhat * g_ref[...].astype(jnp.float32)[None, :] +
                  b_ref[...].astype(jnp.float32)[None, :]).astype(o_ref.dtype)
    # (R, 1) layout: a bare (R,) f32 output tiles T(256) in Mosaic vs XLA's
    # T(512) and fails layout verification on real TPUs
    mean_ref[...] = mean[:, None]
    rstd_ref[...] = rstd[:, None]


def _ln_bwd_kernel(x_ref, g_ref, mean_ref, rstd_ref, do_ref,
                   dx_ref, dg_ref, db_ref):
    i = jax.lax.convert_element_type(pl.program_id(0), jnp.int32)
    x = x_ref[...].astype(jnp.float32)              # (R, F)
    do = do_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)[None, :]
    mean = mean_ref[...]            # (R, 1)
    rstd = rstd_ref[...]
    xhat = (x - mean) * rstd
    dxhat = do * g
    # dx = rstd * (dxhat - mean(dxhat) - xhat * mean(dxhat * xhat))
    m1 = jnp.mean(dxhat, axis=-1, keepdims=True)
    m2 = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
    dx_ref[...] = (rstd * (dxhat - m1 - xhat * m2)).astype(dx_ref.dtype)
    # parameter grads accumulate across the sequential row-block grid
    @pl.when(i == 0)
    def _init():
        dg_ref[...] = jnp.zeros_like(dg_ref)
        db_ref[...] = jnp.zeros_like(db_ref)
    dg_ref[...] = dg_ref[...] + jnp.sum(do * xhat, axis=0)
    db_ref[...] = db_ref[...] + jnp.sum(do, axis=0)


def _ln_fwd(x2, gamma, beta, eps, block_rows, interpret):
    n, f = x2.shape
    nb = n // block_rows
    out, mean, rstd = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((block_rows, f), lambda i: (i, 0)),
            pl.BlockSpec((f,), lambda i: (0,)),
            pl.BlockSpec((f,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, f), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, f), x2.dtype),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2, gamma, beta)
    return out, mean, rstd


def _ln_bwd(x2, gamma, mean, rstd, do2, block_rows, interpret):
    n, f = x2.shape
    nb = n // block_rows
    dx, dg, db = pl.pallas_call(
        _ln_bwd_kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((block_rows, f), lambda i: (i, 0)),
            pl.BlockSpec((f,), lambda i: (0,)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, f), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, f), lambda i: (i, 0)),
            pl.BlockSpec((f,), lambda i: (0,)),       # revisited accumulator
            pl.BlockSpec((f,), lambda i: (0,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, f), x2.dtype),
            jax.ShapeDtypeStruct((f,), jnp.float32),
            jax.ShapeDtypeStruct((f,), jnp.float32),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(x2, gamma, mean, rstd, do2)
    return dx, dg, db


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def layer_norm_pallas(x, gamma, beta, eps=1e-5,
                      block_rows=DEFAULT_BLOCK_ROWS, interpret=False):
    """LayerNorm over the last dim.  x: (..., F); gamma/beta: (F,).
    Requires F % 128 == 0 and rows % block_rows == 0 (supported() gates)."""
    out, _, _ = _ln_core(x, gamma, beta, eps, block_rows, interpret)
    return out


def _ln_core(x, gamma, beta, eps, block_rows, interpret):
    f = x.shape[-1]
    x2 = x.reshape(-1, f)
    n = x2.shape[0]
    br = _ln_resolve_rows(n, f, x.dtype, block_rows)
    if n % br or not _supported_feature_dim(f):
        raise ValueError(
            f"layer_norm_pallas: shape ({n}, {f}) not tileable "
            f"(rows %% {br}, feature %% 128)")
    with x64_scope(False):
        out, mean, rstd = _ln_fwd(x2, gamma, beta, eps, br, interpret)
    return out.reshape(x.shape), mean, rstd


def _ln_vjp_fwd(x, gamma, beta, eps, block_rows, interpret):
    out, mean, rstd = _ln_core(x, gamma, beta, eps, block_rows, interpret)
    return out, (x, gamma, mean, rstd)


def _ln_vjp_bwd(eps, block_rows, interpret, res, g):
    x, gamma, mean, rstd = res
    f = x.shape[-1]
    x2 = x.reshape(-1, f)
    n = x2.shape[0]
    # same deterministic pick as the forward (memoised, so fwd/bwd agree)
    br = _ln_resolve_rows(n, f, x.dtype, block_rows)
    with x64_scope(False):
        dx, dg, db = _ln_bwd(x2, gamma, mean, rstd, g.reshape(-1, f), br,
                             interpret)
    return (dx.reshape(x.shape), dg.astype(gamma.dtype),
            db.astype(gamma.dtype))


layer_norm_pallas.defvjp(_ln_vjp_fwd, _ln_vjp_bwd)


# ---------------------------------------------------------------------------
# row softmax
# ---------------------------------------------------------------------------

def _softmax_kernel(x_ref, o_ref):
    x = x_ref[...].astype(jnp.float32)
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    o_ref[...] = (e / jnp.sum(e, axis=-1, keepdims=True)).astype(o_ref.dtype)


def softmax_pallas(x, block_rows=DEFAULT_BLOCK_ROWS, interpret=False):
    """Numerically-stable softmax over the last dim (f32 statistics).
    Differentiable via jax's autodiff over the kernel's XLA recompute is NOT
    provided — use for inference paths; training softmax lives inside the
    flash-attention kernels."""
    f = x.shape[-1]
    x2 = x.reshape(-1, f)
    n = x2.shape[0]
    br = min(block_rows, n)
    while br > 8 and n % br:
        br //= 2
    if n % br or not _supported_feature_dim(f):
        raise ValueError(
            f"softmax_pallas: shape ({n}, {f}) not tileable")
    with x64_scope(False):
        out = pl.pallas_call(
            _softmax_kernel,
            grid=(n // br,),
            in_specs=[pl.BlockSpec((br, f), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((br, f), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((n, f), x.dtype),
            interpret=interpret,
        )(x2)
    return out.reshape(x.shape)


def _ln_traceable(cand, key):
    """Data-free candidate program for the TPU504 VMEM estimator and the
    trace-tier audit (see flash_attention_pallas._fwd_traceable)."""
    n, f = key["n"], key["f"]
    dtype = jnp.dtype(key["dtype"])
    br = cand["config"]["block_rows"]

    def fn(x, g, b):
        with x64_scope(False):
            return _ln_fwd(x, g, b, 1e-5, br, True)
    sds = jax.ShapeDtypeStruct
    return fn, (sds((n, f), dtype), sds((f,), dtype), sds((f,), dtype))


def _ln_register():
    from . import autotune as at
    at.register_family("ln", _ln_candidates, _ln_runner,
                       cleanup=_ln_runner_cleanup, traceable=_ln_traceable)


_ln_register()
