"""Pallas TPU kernels for the hot ops.

The TPU-native replacement for the reference's hand-fused CUDA kernels
(paddle/fluid/operators/fused/): flash attention, fused layernorm, fused
optimizer updates.  Every kernel has an XLA fallback so the framework runs
anywhere jax runs; kernels self-gate via their ``supported()`` predicates.
"""
from . import autotune  # noqa: F401
from . import flash_attention  # noqa: F401
