"""Kernel autotuner — timed variant/config selection for the Pallas kernels.

The hand-tuned kernel configs (flash 512/512 blocks, hg*d=256 head groups,
the CE lse (row, chunk) layout, LN row blocks) were each found by one-off
on-chip A/Bs (PERF.md rounds 2-5).  That search is exhausted at the *config*
level; what remains is the variant*config product space (bf16 softmax
chains, iota-free band masks, DMA-pipelined K/V — see
flash_attention_pallas.py), which is too large to A/B by hand.  This module
makes the search systematic:

- a **registry** of kernel families (flash_fwd, flash_bwd, flash_bwd_dq,
  flash_bwd_dkv, ce_lse, ln), each exposing the per-key candidate list
  (variant name + config dict; candidate [0] is ALWAYS the hand-tuned
  default) and a runner that executes one candidate on synthetic data;
- **timed selection** at first call per (shape, dtype, platform, causal)
  key: median-of-k on-device wall times per candidate, best wins
  (off by default — enable with FLAGS_autotune=1 / PADDLE_TPU_AUTOTUNE=1,
  or warm explicitly via the CLI);
- a **persistent JSON cache** (`PADDLE_TPU_AUTOTUNE_CACHE`, default
  `~/.cache/paddle_tpu/autotune.json`; set to the empty string to disable)
  plus an in-process memo, so tuning cost is paid once per machine;
- **pin overrides**: `FLAGS_autotune_pin` / `PADDLE_TPU_AUTOTUNE_PIN` =
  ``"family=variant[:k=v,...][;family2=...]"`` forces a candidate without
  timing (highest precedence — above memo, cache and tuning);
- a **CLI**: ``python -m paddle_tpu.kernels.autotune dump|table|clear|warm``
  to inspect, reset or pre-populate the cache.

With tuning disabled, no pin and no cache entry, ``resolve()`` returns the
registered default, so every kernel family lowers to a program bit-identical
to the hand-tuned one (asserted by tests/test_autotune.py).
"""
from __future__ import annotations

import json
import os
import statistics
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..observability import liveness as _liveness

# liveness beacon over one timed candidate-selection run: candidates
# compile + run on device in a loop, and a hung device wedges the warm
# silently.  900s default: a full family sweep pays one compile per
# candidate.
_liveness.declare_beacon(
    "autotune.tune", "one timed autotune selection (compile + time "
    "every candidate for one key)", deadline=900.0)

__all__ = [
    "register_family", "resolve", "tune", "warm", "clear_cache",
    "cache_path", "enabled", "key_str", "families",
]

DEFAULT_CACHE = os.path.join("~", ".cache", "paddle_tpu", "autotune.json")
_CACHE_VERSION = 1

_LOCK = threading.RLock()
_FAMILIES: Dict[str, "KernelFamily"] = {}
#: (family, key_str) -> candidate dict — the in-process memo (hit on every
#: trace after the first; resolve() must stay cheap, it runs at trace time).
#: Holds TUNED/CACHED picks only; defaults memoise separately in
#: _MEMO_DEFAULT so enabling autotune mid-process still tunes keys that
#: were first resolved while tuning was off.
_MEMO: Dict[tuple, Dict[str, Any]] = {}
_MEMO_DEFAULT: Dict[tuple, Dict[str, Any]] = {}
#: (family, key_str) -> candidate as last RETURNED by resolve() — unlike
#: _MEMO this includes pin-resolved candidates, so report() (and bench.py's
#: "autotune" JSON field) reflects what actually ran, pins included
_RESOLVED: Dict[tuple, Dict[str, Any]] = {}
_CACHE: Optional[dict] = None
_CACHE_LOADED_FROM: Optional[str] = None


class KernelFamily:
    """One tunable kernel family.

    ``candidates(key)`` returns the ordered candidate list for a key dict —
    each ``{"variant": str, "config": {...}}``, candidate [0] the hand-tuned
    default.  ``runner(candidate, key)`` builds a zero-arg callable that
    executes the candidate on synthetic data of the key's shape/dtype and
    blocks until the result is ready (None runner = resolvable but not
    timeable — resolve() falls back to the default instead of tuning).
    """

    def __init__(self, name: str,
                 candidates: Callable[[dict], List[dict]],
                 runner: Optional[Callable[[dict, dict], Callable]] = None,
                 cleanup: Optional[Callable[[dict], None]] = None,
                 traceable: Optional[Callable] = None):
        self.name = name
        self.candidates = candidates
        self.runner = runner
        # called with the key after tune() finishes — frees any synthetic
        # device operands the runners cached for that key (they would
        # otherwise pin HBM for the life of the training process)
        self.cleanup = cleanup
        # ``traceable(candidate, key) -> (fn, abstract_args)`` builds the
        # candidate's program for ABSTRACT tracing only (args are
        # ShapeDtypeStructs; nothing executes).  Feeds the TPU504 static
        # VMEM estimator: tune() prices every candidate's BlockSpec
        # working set BEFORE compiling and rejects the unfittable ones,
        # and the trace-tier audit registers one canonical program per
        # variant from the same hook.
        self.traceable = traceable


def register_family(name: str, candidates, runner=None,
                    cleanup=None, traceable=None) -> KernelFamily:
    fam = KernelFamily(name, candidates, runner, cleanup, traceable)
    with _LOCK:
        _FAMILIES[name] = fam
    return fam


def families() -> Dict[str, KernelFamily]:
    return dict(_FAMILIES)


# ---------------------------------------------------------------------------
# keys, flags, pins
# ---------------------------------------------------------------------------

def platform() -> str:
    try:
        import jax
        return jax.default_backend()
    except Exception:
        return "cpu"


def key_str(key: dict) -> str:
    """Canonical cache key: sorted k=v pairs (values stringified)."""
    return ",".join("%s=%s" % (k, key[k]) for k in sorted(key))


def _flag(name):
    try:
        from ..utils import flags as _flags
        return _flags.fast_get(name)
    except Exception:
        return None


def enabled() -> bool:
    """Timed selection on unseen keys (pins/cache/memo are always live)."""
    if os.environ.get("PADDLE_TPU_AUTOTUNE", "").lower() in ("1", "true",
                                                             "yes"):
        return True
    return bool(_flag("autotune"))


def _single_process() -> bool:
    """Lazy in-line tuning is restricted to single-process jobs: hosts of
    a multi-controller SPMD fleet timing candidates independently can pick
    DIFFERENT variants for the same key (wall-clock noise, or a real
    per-host difference) and silently trace divergent programs / diverging
    numerics (bf16chain) across replicas.  Multi-host jobs must pre-tune —
    `python -m paddle_tpu.kernels.autotune warm` on ONE host — and ship
    the resulting cache file to every host (PADDLE_TPU_AUTOTUNE_CACHE):
    cache/pin resolution is deterministic and therefore fleet-consistent.
    """
    try:
        import jax
        return jax.process_count() == 1
    except Exception:
        return True


def _samples() -> int:
    env = os.environ.get("PADDLE_TPU_AUTOTUNE_SAMPLES")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    v = _flag("autotune_samples")
    return max(1, int(v)) if v else 5


def cache_path() -> Optional[str]:
    """Cache file path, or None when persistence is disabled
    (PADDLE_TPU_AUTOTUNE_CACHE set to the empty string)."""
    raw = os.environ.get("PADDLE_TPU_AUTOTUNE_CACHE")
    if raw is None:
        raw = DEFAULT_CACHE
    if not raw:
        return None
    return os.path.expanduser(raw)


def _parse_scalar(v: str):
    for cast in (int, float):
        try:
            return cast(v)
        except ValueError:
            continue
    if v.lower() in ("true", "false"):
        return v.lower() == "true"
    return v


def _pins() -> Dict[str, dict]:
    """``family=variant[:k=v,...];...`` -> {family: {variant, config}}.
    FLAGS_autotune_pin wins over the PADDLE_TPU_AUTOTUNE_PIN env."""
    raw = _flag("autotune_pin") or os.environ.get(
        "PADDLE_TPU_AUTOTUNE_PIN", "")
    out = {}
    for part in str(raw).split(";"):
        part = part.strip()
        if not part or "=" not in part:
            continue
        fam, _, rest = part.partition("=")
        variant, _, cfg_s = rest.partition(":")
        config = {}
        for kv in cfg_s.split(","):
            if "=" in kv:
                ck, _, cv = kv.partition("=")
                config[ck.strip()] = _parse_scalar(cv.strip())
        out[fam.strip()] = {"variant": variant.strip(), "config": config}
    return out


# ---------------------------------------------------------------------------
# persistent cache
# ---------------------------------------------------------------------------

def _load_cache() -> dict:
    global _CACHE, _CACHE_LOADED_FROM
    path = cache_path()
    with _LOCK:
        if _CACHE is not None and _CACHE_LOADED_FROM == path:
            return _CACHE
    # file I/O outside the lock (blocking while locked stalls every
    # autotune lookup behind a slow disk): racing first loads both read
    # the file; the loser re-checks below and adopts the winner's copy
    data = {"version": _CACHE_VERSION, "families": {}}
    if path and os.path.isfile(path):
        try:
            with open(path) as f:
                loaded = json.load(f)
            if isinstance(loaded, dict) and \
                    loaded.get("version") == _CACHE_VERSION:
                data = loaded
        except (OSError, ValueError):
            pass  # unreadable/corrupt cache = empty cache
    with _LOCK:
        if _CACHE is None or _CACHE_LOADED_FROM != path:
            _CACHE = data
            _CACHE_LOADED_FROM = path
        return _CACHE


def _save_cache():
    path = cache_path()
    if not path or _CACHE is None:
        return
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp.%d" % os.getpid()
        with open(tmp, "w") as f:
            json.dump(_CACHE, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        pass  # read-only FS etc. — memo still holds the result


def clear_cache(in_process_too: bool = True):
    """Delete the persistent cache file (and the in-process memo)."""
    global _CACHE, _CACHE_LOADED_FROM
    with _LOCK:
        path = cache_path()
        if path and os.path.isfile(path):
            os.remove(path)
        _CACHE = None
        _CACHE_LOADED_FROM = None
        if in_process_too:
            _MEMO.clear()
            _MEMO_DEFAULT.clear()


# ---------------------------------------------------------------------------
# timing + selection
# ---------------------------------------------------------------------------

def _time_callable(fn: Callable, samples: int) -> float:
    """Median-of-``samples`` wall ms.  ``fn`` must block until its device
    work is done (runners call jax.block_until_ready).  One untimed warmup
    run absorbs compilation."""
    fn()
    times = []
    for _ in range(samples):
        t0 = time.perf_counter()
        fn()
        times.append((time.perf_counter() - t0) * 1000.0)
    return float(statistics.median(times))


def _cand_sig(cand: dict) -> str:
    cfg = cand.get("config", {})
    return cand["variant"] + ":" + ",".join(
        "%s=%s" % (k, cfg[k]) for k in sorted(cfg))


def _record_event(name: str):
    try:
        from ..profiler import RecordEvent
        return RecordEvent(name)
    except Exception:
        import contextlib
        return contextlib.nullcontext()


def _vmem_reject(fam: "KernelFamily", cand: dict, key: dict
                 ) -> Optional[str]:
    """Non-empty rejection reason when the candidate's static VMEM
    footprint (TPU504 estimator, paddle_tpu.analysis.trace.vmem) exceeds
    the per-core budget.  Estimator problems never block tuning — a
    candidate we cannot price is timed normally (and fails on-device the
    way it always did)."""
    if fam.traceable is None:
        return None
    try:
        from ..analysis.trace.vmem import fits_vmem
        fn, args = fam.traceable(cand, key)
        ok, why = fits_vmem(fn, *args)
    except Exception:
        return None
    return None if ok else "rejected: vmem (%s)" % why


def tune(family_name: str, key: dict, persist: bool = True,
         verbose: bool = False, run_cleanup: bool = True) -> dict:
    """Time every candidate for ``key`` and select the fastest.

    Candidates whose build/run raises (e.g. a VMEM overflow on the real
    chip) are recorded as failed and skipped.  The winner is memoised and —
    when ``persist`` — written to the JSON cache with the full timing table.
    ``run_cleanup=False`` defers the family's operand-cache cleanup to the
    caller (warm() batches several families over the same key and would
    otherwise rebuild the shared synthetic operands per family).
    """
    fam = _FAMILIES[family_name]
    if fam.runner is None:
        raise ValueError("family %r has no runner registered" % family_name)
    cands = fam.candidates(key)
    if not cands:
        raise ValueError("family %r produced no candidates for %s"
                         % (family_name, key))
    ks = key_str(key)
    samples = _samples()
    timings: Dict[str, Any] = {}
    best, best_ms = None, None
    from ..observability import registry as _obs
    _tune_t0 = time.perf_counter()
    try:
        # tune() is cold-path: fetching the beacon per call is fine
        with _liveness.beacon("autotune.tune"), \
                _record_event("autotune::%s::%s" % (family_name, ks)):
            for cand in cands:
                sig = _cand_sig(cand)
                rejected = _vmem_reject(fam, cand, key)
                if rejected:
                    # TPU504 pre-compile gate: the static BlockSpec
                    # working set cannot fit per-core VMEM — recorded in
                    # the timing table instead of faulting on-device
                    # mid-warm (and wasting a TPU session on it)
                    timings[sig] = rejected
                    if verbose:
                        print("  %-48s %s" % (sig, rejected))
                    continue
                try:
                    fn = fam.runner(cand, key)
                    ms = _time_callable(fn, samples)
                except Exception as e:  # candidate illegal at this key
                    timings[sig] = "failed: %s" % type(e).__name__
                    continue
                timings[sig] = round(ms, 4)
                if verbose:
                    print("  %-48s %10.3f ms" % (sig, ms))
                if best_ms is None or ms < best_ms:
                    best, best_ms = cand, ms
    finally:
        _obs.histogram("autotune.tune_seconds").observe(
            time.perf_counter() - _tune_t0)
        if run_cleanup and fam.cleanup is not None:
            try:
                fam.cleanup(key)
            except Exception:
                pass
    if best is None:
        # nothing timed successfully.  A statically VMEM-rejected
        # candidate must NEVER be the fallback — the gate just proved it
        # faults on device; fall back to the first candidate that at
        # least fits (runtime failures may be transient/key-specific),
        # and fail loudly when no candidate fits at all.
        vmem_rejected = {sig for sig, v in timings.items()
                         if isinstance(v, str)
                         and v.startswith("rejected: vmem")}
        best = next((c for c in cands
                     if _cand_sig(c) not in vmem_rejected), None)
        if best is None:
            raise ValueError(
                "autotune %s [%s]: no candidate fits per-core VMEM — %s"
                % (family_name, ks, "; ".join(
                    "%s -> %s" % kv for kv in sorted(timings.items()))))
        best_ms = float("nan")
    entry = {"variant": best["variant"], "config": dict(best["config"]),
             "ms": None if best_ms != best_ms else round(best_ms, 4),
             "samples": samples, "timings": timings}
    with _LOCK:
        _MEMO[(family_name, ks)] = {"variant": entry["variant"],
                                    "config": dict(entry["config"])}
        if persist:
            cache = _load_cache()
            cache.setdefault("families", {}).setdefault(
                family_name, {})[ks] = entry
            _save_cache()
    return _MEMO[(family_name, ks)]


def resolve(family_name: str, key: dict) -> dict:
    """The hot-path lookup the kernel wrappers call at trace time.

    Precedence: pin override > in-process memo > persistent cache > timed
    selection (only when autotuning is enabled) > registered default.
    Always returns ``{"variant": str, "config": dict}``.
    """
    fam = _FAMILIES.get(family_name)
    if fam is None:
        raise KeyError("unknown autotune family %r" % family_name)
    ks = key_str(key)

    def _log(cand):
        with _LOCK:
            _RESOLVED[(family_name, ks)] = cand
        return cand

    from ..observability import registry as _obs
    pin = _pins().get(family_name)
    if pin is not None:
        default = fam.candidates(key)[0]
        _obs.counter("autotune.cache_hits").inc()
        return _log({"variant": pin["variant"] or default["variant"],
                     "config": {**default["config"], **pin["config"]}})
    with _LOCK:
        hit = _MEMO.get((family_name, ks))
        if hit is not None:
            _RESOLVED[(family_name, ks)] = hit
            _obs.counter("autotune.cache_hits").inc()
            return hit
        entry = _load_cache().get("families", {}).get(
            family_name, {}).get(ks)
        if entry is not None:
            cand = {"variant": entry["variant"],
                    "config": dict(entry["config"])}
            _MEMO[(family_name, ks)] = cand
            _RESOLVED[(family_name, ks)] = cand
            _obs.counter("autotune.cache_hits").inc()
            return cand
    _obs.counter("autotune.cache_misses").inc()
    if enabled() and fam.runner is not None and _single_process():
        return _log(tune(family_name, key))
    with _LOCK:
        default = _MEMO_DEFAULT.get((family_name, ks))
        if default is None:
            default = fam.candidates(key)[0]
            _MEMO_DEFAULT[(family_name, ks)] = default
    return _log(default)


def report() -> Dict[str, Dict[str, dict]]:
    """Snapshot of every candidate resolved in THIS process (pins
    included), keyed family -> key_str -> candidate — what bench.py
    attaches to its JSON line so the measured throughput is tied to the
    configs that ran."""
    with _LOCK:
        out: Dict[str, Dict[str, dict]] = {}
        for (fam, ks), cand in sorted(_RESOLVED.items()):
            out.setdefault(fam, {})[ks] = {"variant": cand["variant"],
                                           "config": dict(cand["config"])}
        return out


# ---------------------------------------------------------------------------
# warm — pre-populate the cache for the bench-standard keys
# ---------------------------------------------------------------------------

def _import_kernel_families():
    """Family registration happens at kernel-module import."""
    from . import (ce_pallas, decode_attention,  # noqa: F401
                   flash_attention_pallas, norm_pallas)


def standard_keys() -> List[tuple]:
    """(family, key) pairs for the GPT-2 345M bench shapes — what the CLI
    warms by default (override shapes via the warm subcommand flags)."""
    _import_kernel_families()
    from . import flash_attention_pallas as fap
    plat = platform()
    dtype = "bfloat16" if plat == "tpu" else "float32"
    out = []
    for fam_name in ("flash_fwd", "flash_bwd", "flash_bwd_dq",
                     "flash_bwd_dkv"):
        out.append((fam_name, fap.autotune_key(
            b=8, s=1024, sk=1024, h=16, d=64, dtype=dtype, causal=True)))
    from . import ce_pallas as cep
    out.append(("ce_lse", cep.autotune_key(n=8192, v=50304, dtype=dtype)))
    from . import norm_pallas as nop
    out.append(("ln", nop.autotune_key(n=8192, f=1024, dtype=dtype)))
    from . import decode_attention as dat
    # the serving decode step's attention at the bench-standard serving
    # shape (8 slots, 1024-token cache, GPT-2 345M heads)
    out.append(("decode_attn", dat.autotune_key(
        slots=8, t=1024, h=16, d=64, qlen=1, dtype=dtype)))
    # the paged layout at the same serving shape: 64-token pages, 16
    # pages per slot, pool sized for all 8 slots at full depth
    out.append(("decode_attn_paged", dat.paged_autotune_key(
        slots=8, pages=128, page_size=64, max_pages=16, h=16, d=64,
        qlen=1, dtype=dtype)))
    # int8 KV (ISSUE 8): the q8 gather schedules tune under their own
    # key, and the speculative verify shape (qlen = k+1) tunes the
    # multi-token masked path the verify program runs
    out.append(("decode_attn_paged", dat.paged_autotune_key(
        slots=8, pages=128, page_size=64, max_pages=16, h=16, d=64,
        qlen=1, dtype=dtype, kv_dtype="int8")))
    out.append(("decode_attn_paged", dat.paged_autotune_key(
        slots=8, pages=128, page_size=64, max_pages=16, h=16, d=64,
        qlen=5, dtype=dtype)))
    # tensor-parallel serving (ISSUE 12): the tp=2 sharded decode's
    # PER-SHARD shape (8 of the 16 heads per chip) tunes under its own
    # key so the next on-chip warm covers the multi-chip engine too
    out.append(("decode_attn_paged", dat.paged_autotune_key(
        slots=8, pages=128, page_size=64, max_pages=16, h=16, d=64,
        qlen=1, dtype=dtype, tp=2)))
    # fp8 KV (ISSUE 20) deliberately adds NO standard key: its codes
    # ride the exact q8 variant structure already registered under the
    # int8 key (another key would duplicate those pallas programs in
    # the trace registry), and the bench warms its own key on demand
    # (autotune_key carries kv_dtype, so the grids can never collide)
    # decomposed collective-matmul rings (ISSUE 20): the chunk count of
    # the tp=2 row ring at GPT-2 345M's projection shape — the family
    # exposes no pallas traceable (it is a shard_map schedule, not a
    # kernel), so this key is warm()-only and adds no registry programs
    from ..distributed import mp_overlap as mpo
    out.append(("mp_overlap", mpo.autotune_key(
        kind="row", m=8, k=4096 // 2, n=1024, n_dev=2, dtype=dtype)))
    return out


def warm(pairs=None, verbose: bool = True) -> List[dict]:
    """Tune every (family, key) pair (default: the bench-standard set) and
    persist the results.  Per-family operand-cache cleanups are deferred to
    the END of the batch: the four flash families share one per-key
    synthetic operand set, and cleaning between families would rebuild it
    (and re-run the baseline forward) four times."""
    _import_kernel_families()
    if pairs is None:
        pairs = standard_keys()
    results = []
    try:
        for fam_name, key in pairs:
            if verbose:
                print("tuning %s [%s] on %s ..." % (fam_name, key_str(key),
                                                    platform()))
            cand = tune(fam_name, key, verbose=verbose, run_cleanup=False)
            if verbose:
                print("  -> %s %s" % (cand["variant"], cand["config"]))
            results.append(cand)
    finally:
        for fam_name, key in pairs:
            fam = _FAMILIES.get(fam_name)
            if fam is not None and fam.cleanup is not None:
                try:
                    fam.cleanup(key)
                except Exception:
                    pass
    return results


# ---------------------------------------------------------------------------
# CLI: python -m paddle_tpu.kernels.autotune {dump,table,clear,warm}
# ---------------------------------------------------------------------------

def _cli_table():
    cache = _load_cache()
    fams = cache.get("families", {})
    if not any(fams.values()):
        print("autotune cache empty (%s)" % (cache_path() or "disabled"))
        return
    for fam_name in sorted(fams):
        for ks, entry in sorted(fams[fam_name].items()):
            print("%s [%s]" % (fam_name, ks))
            print("  chosen: %s %s  (median %s ms of %s)" % (
                entry["variant"], entry["config"], entry.get("ms"),
                entry.get("samples")))
            for sig, ms in sorted(entry.get("timings", {}).items(),
                                  key=lambda kv: (isinstance(kv[1], str),
                                                  kv[1])):
                print("    %-52s %s" % (sig, ms if isinstance(ms, str)
                                        else "%.3f ms" % ms))


def _cli_main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.kernels.autotune",
        description="Inspect, clear or warm the kernel autotune cache.")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("dump", help="print the raw cache JSON")
    sub.add_parser("table", help="print a per-key timing table")
    sub.add_parser("clear", help="delete the cache file")
    w = sub.add_parser("warm", help="run timed selection for the "
                       "bench-standard keys on this platform")
    w.add_argument("--family", help="warm only this family")
    w.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)
    if args.cmd == "dump":
        print(json.dumps(_load_cache(), indent=1, sort_keys=True))
    elif args.cmd == "table":
        _cli_table()
    elif args.cmd == "clear":
        path = cache_path()
        clear_cache()
        print("cleared %s" % (path or "(persistence disabled)"))
    elif args.cmd == "warm":
        pairs = standard_keys()
        if args.family:
            pairs = [(f, k) for f, k in pairs if f == args.family]
            if not pairs:
                raise SystemExit("no standard key for family %r"
                                 % args.family)
        warm(pairs, verbose=not args.quiet)
        _cli_table()
    return 0


if __name__ == "__main__":
    raise SystemExit(_cli_main())
