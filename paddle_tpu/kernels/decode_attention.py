"""Decode attention — length-masked attention over the serving caches.

The serving decode step attends ``q: (slots, s, heads, d)`` (``s`` is 1
for plain decode) against the KV cache with each slot masked to its
valid prefix: query offset ``j`` of a slot with pre-append length ``n``
attends keys ``t <= n + j``.  Two cache layouts, two autotune families:

* ``decode_attn`` — the slotted contiguous cache ``k/v: (slots,
  max_len, heads, d)``.
* ``decode_attn_paged`` — the paged pool ``k/v: (num_pages, page_size,
  heads, d)`` plus a per-slot ``page_table: (slots, max_pages)`` int32
  (one layer's slice of ``serving.cache.PagedKVCache``): keys are
  *gathered* through the table, so each slot reads its own mapped pages
  (shared prefix pages included) and the read bound a page-aware
  schedule pays scales with mapped pages, not ``max_len``.

Both are registered with the autotuner so the variant choice can be
tuned on-chip next TPU session (PERF.md protocol).  Variants are
XLA-level (no Pallas) — at decode shapes the op is bandwidth-bound on
the K/V read, which XLA already streams well; what is worth tuning is
the *schedule*:

* ``masked`` / ``paged_gather`` (defaults) — one-shot: (gather then)
  full ``(slots, h, s, T)`` masked logits, f32 softmax statistics.
  Minimal launches; peak memory O(slots*h*s*T) f32 plus, for the paged
  gather, the materialized ``(slots, max_pages*page_size, h, d)`` keys.
* ``chunked`` / ``paged_chunked`` — online-softmax streamed over key
  chunks (the flash recurrence along the time axis); the paged form
  gathers ``pages_per_block`` pages per scan step, so the gathered
  working set is O(block) instead of O(max_len).  Candidate win at long
  ``max_len`` where the one-shot buffers stop fitting close to the
  compute.

**int8 KV (ISSUE 8)** — when the cache pool is int8 codes + per-(row,
head) f32 scales (``serving.cache`` ``kv_dtype="int8"``), the q8
variants — ``masked_q8``/``chunked_q8`` (slotted) and
``paged_gather_q8``/``paged_chunked_q8`` (paged) — **dequantize inline
in the gather**: the HBM read moves int8 codes (+ one f32 scale per
row-head, ~6% at head_dim 64), i.e. roughly HALF the bf16 pool's
bytes, and the dequantized values exist only as a fused compute-local
intermediate.  The autotune key gains ``kv_dtype`` so quantized and
unquantized schedules tune independently.

All variants keep the bf16-region dtype discipline TPU501 audits:
``dot_general`` runs on the input dtype with ``preferred_element_type``
f32 accumulation, the softmax statistic chain stays f32, and ``p`` is
cast back to the input dtype before the second matmul.  The q8 dequant
multiplies int8->f32-converted codes by f32 scales and casts ONCE to
the compute dtype — no bf16->f32 upcast, so the bf16-region audit stays
clean by construction.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["decode_attention", "paged_decode_attention", "autotune_key",
           "paged_autotune_key", "supported_block_ts",
           "supported_pages_per_block", "quantize_kv", "dequantize_kv"]

_NEG_INF = -1e30

# -- quantized KV grids (the ONE canonical definition — serving.cache
#    imports these, and the autotune runners synthesize operands through
#    the same math, so a grid can never drift between the cache's writes
#    and the kernels' reads).  ISSUE 20 cashes PR 8's "fp8-ready"
#    promise: e4m3 shares the whole symmetric-amax pipeline; the grid
#    constant (448 vs 127) and the code dtype are the only deltas -------

_Q_MAX = 127.0          # int8 symmetric grid
_FP8_MAX = 448.0        # float8_e4m3fn finite max (OCP E4M3: no inf,
                        # values past ±448 encode NaN — clip, never wrap)

#: kv_dtype key values that select the quantized (codes + scales) paths
_QUANT_KV_DTYPES = ("int8", "float8_e4m3fn")


def _grid_for(code_dtype):
    dt = jnp.dtype(code_dtype)
    if dt == jnp.dtype(jnp.int8):
        return dt, _Q_MAX
    if dt == jnp.dtype(jnp.float8_e4m3fn):
        return dt, _FP8_MAX
    raise ValueError("unsupported KV code dtype %r (int8 or "
                     "float8_e4m3fn)" % (code_dtype,))


def quantize_kv(x, code_dtype=jnp.int8):
    """Quantize ``x: (..., heads, head_dim)`` to codes + per-(..., head)
    f32 scales (symmetric amax/grid-max).  int8 keeps PR 8's exact math
    (round then belt-and-braces clip: ``|x| <= amax`` bounds ``x/scale``
    at 127 up to one f32 rounding).  fp8/e4m3 clips BEFORE the cast —
    the format saturates to NaN past ±448, so an unclipped one-ulp
    overshoot would poison the whole attention row — and lets the cast
    itself do the round-to-nearest-even onto the e4m3 grid."""
    dt, qmax = _grid_for(code_dtype)
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, jnp.asarray(1e-30, jnp.float32)) / qmax
    scaled = xf / scale[..., None]
    if dt == jnp.dtype(jnp.int8):
        q = jnp.clip(jnp.round(scaled), -qmax, qmax)
    else:
        q = jnp.clip(scaled, -qmax, qmax)
    return q.astype(dt), scale


def dequantize_kv(codes, scales, dtype):
    """Inverse of :func:`quantize_kv` in the given compute dtype.  The
    multiply runs f32 (int8->f32 is exact and e4m3->f32 is a widening
    cast; the single trailing cast to bf16 rounds below the quantization
    error) — TPU501-clean: no bf16->f32 upcast is involved."""
    return (codes.astype(jnp.float32) * scales[..., None]).astype(dtype)


def autotune_key(slots, t, h, d, qlen, dtype, kv_dtype=None, tp=1):
    from . import autotune as at
    key = {"slots": int(slots), "t": int(t), "h": int(h), "d": int(d),
           "qlen": int(qlen), "dtype": str(jnp.dtype(dtype)),
           "platform": at.platform()}
    if kv_dtype is not None:
        # only quantized keys carry the field: unquantized keys (and any
        # persisted cache entries for them) stay byte-identical to PR 7's
        key["kv_dtype"] = str(jnp.dtype(kv_dtype))
    return _apply_tp(key, tp)


def _apply_tp(key, tp):
    """Tensor-parallel keys price the PER-SHARD program: under the head-
    partitioned serving mesh each chip runs ``h / tp`` heads, so the
    timed runner operands, the VMEM working set TPU504-style pricing
    sees, and any persisted winner all describe what ONE device
    executes.  ``tp`` stays in the key so a sharded winner can never be
    served to (or clobber) the unsharded shape — and tp=1 keys stay
    byte-identical to the pre-TP cache entries."""
    tp = int(tp)
    if tp > 1:
        if key["h"] % tp:
            raise ValueError("heads %d not divisible by tp %d"
                             % (key["h"], tp))
        key["h"] //= tp
        key["tp"] = tp
    return key


# dequantize-inline shorthand for the q8 variants below
_deq = dequantize_kv


def _scale(scale, d):
    if scale is None:
        return jnp.asarray(1.0 / (float(d) ** 0.5), jnp.float32)
    return jnp.asarray(scale, jnp.float32)


def _masked(q, k, v, pos, scale):
    """One-shot masked softmax attention (f32 statistics)."""
    s, t = q.shape[1], k.shape[1]
    # (B, s, H, D) x (B, T, H, D) -> (B, H, s, T), f32 accumulation
    logits = jnp.einsum("bqhd,bthd->bhqt", q, k,
                        preferred_element_type=jnp.float32)
    logits = logits * _scale(scale, q.shape[-1])
    t_ids = jnp.arange(t, dtype=jnp.int32)
    q_pos = pos[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
    valid = t_ids[None, None, None, :] <= q_pos[:, None, :, None]
    logits = jnp.where(valid, logits, jnp.asarray(_NEG_INF, jnp.float32))
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqt,bthd->bqhd", p, v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def _online_step(carry, q, k_blk, v_blk, t_ids, q_pos, sc):
    """One flash-recurrence step over a key block: f32 statistics carry
    ``(m, l, acc)``; ``t_ids: (block,)`` are the block's global key
    positions, masked against ``q_pos: (b, s)``."""
    m, l, acc = carry
    logits = jnp.einsum("bqhd,bthd->bhqt", q, k_blk,
                        preferred_element_type=jnp.float32) * sc
    valid = t_ids[None, None, None, :] <= q_pos[:, None, :, None]
    logits = jnp.where(valid, logits, jnp.asarray(_NEG_INF, jnp.float32))
    m_blk = jnp.max(logits, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    # m_new can stay -inf-ish for rows with no valid key yet; the
    # exp of (NEG_INF - NEG_INF) = exp(0) rows are zeroed by `valid`
    p = jnp.exp(logits - m_new[..., None])
    p = jnp.where(valid, p, jnp.zeros((), jnp.float32))
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqt,bthd->bhqd", p.astype(q.dtype), v_blk,
                    preferred_element_type=jnp.float32)
    acc_new = acc * corr[..., None] + pv
    return m_new, l_new, acc_new


def _online_init(b, h, s, d):
    return (jnp.full((b, h, s), _NEG_INF, jnp.float32),
            jnp.zeros((b, h, s), jnp.float32),
            jnp.zeros((b, h, s, d), jnp.float32))


def _online_finish(carry, q_dtype):
    m, l, acc = carry
    out = acc / jnp.maximum(l, jnp.asarray(1e-30, jnp.float32))[..., None]
    return jnp.moveaxis(out, 1, 2).astype(q_dtype)  # (B,H,s,D)->(B,s,H,D)


def _chunked(q, k, v, pos, scale, block_t):
    """Online-softmax over key chunks (flash recurrence along time)."""
    b, s, h, d = q.shape
    t = k.shape[1]
    n_chunks = t // block_t
    sc = _scale(scale, d)
    q_pos = pos[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
    kc = k.reshape(b, n_chunks, block_t, h, d)
    vc = v.reshape(b, n_chunks, block_t, h, d)
    # scan carries f32 statistics; chunks are the scanned axis
    kc = jnp.moveaxis(kc, 1, 0)
    vc = jnp.moveaxis(vc, 1, 0)

    def body(carry, xs):
        k_blk, v_blk, c = xs
        t_ids = c * block_t + jnp.arange(block_t, dtype=jnp.int32)
        return _online_step(carry, q, k_blk, v_blk, t_ids, q_pos, sc), None

    chunk_ids = jnp.arange(n_chunks, dtype=jnp.int32)
    carry, _ = jax.lax.scan(body, _online_init(b, h, s, d),
                            (kc, vc, chunk_ids))
    return _online_finish(carry, q.dtype)


def supported_block_ts(t):
    return [bt for bt in (128, 256, 512) if t % bt == 0 and bt < t]


def _masked_q8(q, k8, ks, v8, vs, pos, scale):
    """One-shot over the int8 slotted cache: dequantize the (slots, T)
    rows inline (the HBM read is the int8 codes + scale rows) and run
    the masked softmax."""
    return _masked(q, _deq(k8, ks, q.dtype), _deq(v8, vs, q.dtype),
                   pos, scale)


def _chunked_q8(q, k8, ks, v8, vs, pos, scale, block_t):
    """Online-softmax over int8 key chunks: each scan step dequantizes
    ONE block, so the dequantized working set is O(block_t)."""
    b, s, h, d = q.shape
    t = k8.shape[1]
    n_chunks = t // block_t
    sc = _scale(scale, d)
    q_pos = pos[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
    kc = jnp.moveaxis(k8.reshape(b, n_chunks, block_t, h, d), 1, 0)
    vc = jnp.moveaxis(v8.reshape(b, n_chunks, block_t, h, d), 1, 0)
    ksc = jnp.moveaxis(ks.reshape(b, n_chunks, block_t, h), 1, 0)
    vsc = jnp.moveaxis(vs.reshape(b, n_chunks, block_t, h), 1, 0)

    def body(carry, xs):
        k_blk, v_blk, ks_blk, vs_blk, c = xs
        t_ids = c * block_t + jnp.arange(block_t, dtype=jnp.int32)
        return _online_step(carry, q, _deq(k_blk, ks_blk, q.dtype),
                            _deq(v_blk, vs_blk, q.dtype), t_ids, q_pos,
                            sc), None

    chunk_ids = jnp.arange(n_chunks, dtype=jnp.int32)
    carry, _ = jax.lax.scan(body, _online_init(b, h, s, d),
                            (kc, vc, ksc, vsc, chunk_ids))
    return _online_finish(carry, q.dtype)


def _candidates(key):
    if key.get("kv_dtype") in _QUANT_KV_DTYPES:
        out = [{"variant": "masked_q8", "config": {}}]
        for bt in supported_block_ts(key["t"]):
            out.append({"variant": "chunked_q8",
                        "config": {"block_t": bt}})
        return out
    out = [{"variant": "masked", "config": {}}]
    for bt in supported_block_ts(key["t"]):
        out.append({"variant": "chunked", "config": {"block_t": bt}})
    return out


def _dispatch(cand, q, k, v, pos, scale, k_scales=None, v_scales=None):
    if k_scales is not None:
        if cand.get("variant") == "chunked_q8":
            bt = int(cand.get("config", {}).get("block_t", 0))
            if bt > 0 and k.shape[1] % bt == 0:
                return _chunked_q8(q, k, k_scales, v, v_scales, pos,
                                   scale, bt)
            # invalid cached/pinned config: fall back, never fault
        return _masked_q8(q, k, k_scales, v, v_scales, pos, scale)
    if cand.get("variant") == "chunked":
        bt = int(cand.get("config", {}).get("block_t", 0))
        if bt > 0 and k.shape[1] % bt == 0:
            return _chunked(q, k, v, pos, scale, bt)
        # invalid cached/pinned config for this key: fall back, never fault
    return _masked(q, k, v, pos, scale)


def decode_attention(q, k, v, lengths, scale=None, k_scales=None,
                     v_scales=None, tp=1):
    """Length-masked attention for the slotted decode step (raw arrays).

    q: (slots, s, heads, d); k/v: (slots, max_len, heads, d);
    lengths: (slots,) int32 — each slot's PRE-append valid length (the new
    rows were already written at [lengths, lengths+s), so query offset j
    attends keys t <= lengths + j).  For the int8 cache, k/v are the code
    arrays and ``k_scales/v_scales: (slots, max_len, heads)`` f32 select
    the q8 variants (dequantize inline).  ``tp`` is the tensor-parallel
    degree of the enclosing sharded program: trace-time shapes are
    GLOBAL under jit-with-sharding, so the key records the per-shard
    head count each device actually runs.
    """
    from . import autotune as at
    kv_dtype = None if k_scales is None else k.dtype
    key = autotune_key(q.shape[0], k.shape[1], q.shape[2], q.shape[3],
                       q.shape[1], q.dtype, kv_dtype=kv_dtype, tp=tp)
    cand = at.resolve("decode_attn", key)
    return _dispatch(cand, q, k, v, lengths, scale,
                     k_scales=k_scales, v_scales=v_scales)


# ---------------------------------------------------------------------------
# paged variants (the decode_attn_paged family)
# ---------------------------------------------------------------------------


def paged_autotune_key(slots, pages, page_size, max_pages, h, d, qlen,
                       dtype, kv_dtype=None, tp=1):
    from . import autotune as at
    key = {"slots": int(slots), "pages": int(pages),
           "page_size": int(page_size), "max_pages": int(max_pages),
           "h": int(h), "d": int(d), "qlen": int(qlen),
           "dtype": str(jnp.dtype(dtype)), "platform": at.platform()}
    if kv_dtype is not None:
        key["kv_dtype"] = str(jnp.dtype(kv_dtype))
    return _apply_tp(key, tp)


def _gather_pages(kp, table):
    """kp: (num_pages, P, h, d); table: (B, n) int32 -> (B, n*P, h, d).
    Unmapped entries hold 0: page 0's bytes are gathered and discarded
    by the length mask downstream."""
    b, n = table.shape
    P, h, d = kp.shape[1], kp.shape[2], kp.shape[3]
    return kp[table].reshape(b, n * P, h, d)


def _paged_gather(q, kp, vp, table, pos, scale):
    """One-shot: gather every mapped page, then the masked softmax."""
    return _masked(q, _gather_pages(kp, table), _gather_pages(vp, table),
                   pos, scale)


def _paged_chunked(q, kp, vp, table, pos, scale, pages_per_block):
    """Online-softmax over page blocks: each scan step gathers
    ``pages_per_block`` pages per slot and folds them into the flash
    recurrence — O(block) gathered working set instead of the one-shot
    ``max_pages * page_size`` materialization."""
    b, s, h, d = q.shape
    P = int(kp.shape[1])
    max_pages = int(table.shape[1])
    m = int(pages_per_block)
    n_chunks = max_pages // m
    block = m * P
    sc = _scale(scale, d)
    q_pos = pos[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
    tb = jnp.moveaxis(table.reshape(b, n_chunks, m), 1, 0)  # (C, b, m)

    def body(carry, xs):
        ids, c = xs
        k_blk = _gather_pages(kp, ids)
        v_blk = _gather_pages(vp, ids)
        t_ids = c * block + jnp.arange(block, dtype=jnp.int32)
        return _online_step(carry, q, k_blk, v_blk, t_ids, q_pos, sc), None

    chunk_ids = jnp.arange(n_chunks, dtype=jnp.int32)
    carry, _ = jax.lax.scan(body, _online_init(b, h, s, d),
                            (tb, chunk_ids))
    return _online_finish(carry, q.dtype)


def supported_pages_per_block(max_pages):
    return [m for m in (1, 2, 4, 8) if max_pages % m == 0 and m < max_pages]


def _gather_scale_pages(sp, table):
    """sp: (num_pages, P, h) f32 scale pool; table: (B, n) int32 ->
    (B, n*P, h) — the scale-row companion of :func:`_gather_pages`."""
    b, n = table.shape
    P, h = sp.shape[1], sp.shape[2]
    return sp[table].reshape(b, n * P, h)


def _paged_gather_q8(q, kp, ks, vp, vs, table, pos, scale):
    """One-shot over the int8 pool: gather every mapped page's codes AND
    scale rows, dequantize inline, then the masked softmax — the HBM
    read is the int8 pages plus the (head_dim/4)x-smaller scale pages."""
    return _masked(q,
                   _deq(_gather_pages(kp, table),
                        _gather_scale_pages(ks, table), q.dtype),
                   _deq(_gather_pages(vp, table),
                        _gather_scale_pages(vs, table), q.dtype),
                   pos, scale)


def _paged_chunked_q8(q, kp, ks, vp, vs, table, pos, scale,
                      pages_per_block):
    """Online-softmax over int8 page blocks: each scan step gathers and
    dequantizes ``pages_per_block`` pages per slot — O(block)
    dequantized working set."""
    b, s, h, d = q.shape
    P = int(kp.shape[1])
    max_pages = int(table.shape[1])
    m = int(pages_per_block)
    n_chunks = max_pages // m
    block = m * P
    sc = _scale(scale, d)
    q_pos = pos[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
    tb = jnp.moveaxis(table.reshape(b, n_chunks, m), 1, 0)  # (C, b, m)

    def body(carry, xs):
        ids, c = xs
        k_blk = _deq(_gather_pages(kp, ids), _gather_scale_pages(ks, ids),
                     q.dtype)
        v_blk = _deq(_gather_pages(vp, ids), _gather_scale_pages(vs, ids),
                     q.dtype)
        t_ids = c * block + jnp.arange(block, dtype=jnp.int32)
        return _online_step(carry, q, k_blk, v_blk, t_ids, q_pos, sc), None

    chunk_ids = jnp.arange(n_chunks, dtype=jnp.int32)
    carry, _ = jax.lax.scan(body, _online_init(b, h, s, d),
                            (tb, chunk_ids))
    return _online_finish(carry, q.dtype)


def _paged_candidates(key):
    if key.get("kv_dtype") in _QUANT_KV_DTYPES:
        out = [{"variant": "paged_gather_q8", "config": {}}]
        for m in supported_pages_per_block(key["max_pages"]):
            out.append({"variant": "paged_chunked_q8",
                        "config": {"pages_per_block": m}})
        return out
    out = [{"variant": "paged_gather", "config": {}}]
    for m in supported_pages_per_block(key["max_pages"]):
        out.append({"variant": "paged_chunked",
                    "config": {"pages_per_block": m}})
    return out


def _dispatch_paged(cand, q, kp, vp, table, pos, scale, k_scales=None,
                    v_scales=None):
    if k_scales is not None:
        if cand.get("variant") == "paged_chunked_q8":
            m = int(cand.get("config", {}).get("pages_per_block", 0))
            if m > 0 and table.shape[1] % m == 0:
                return _paged_chunked_q8(q, kp, k_scales, vp, v_scales,
                                         table, pos, scale, m)
            # invalid cached/pinned config: fall back, never fault
        return _paged_gather_q8(q, kp, k_scales, vp, v_scales, table, pos,
                                scale)
    if cand.get("variant") == "paged_chunked":
        m = int(cand.get("config", {}).get("pages_per_block", 0))
        if m > 0 and table.shape[1] % m == 0:
            return _paged_chunked(q, kp, vp, table, pos, scale, m)
        # invalid cached/pinned config for this key: fall back, never fault
    return _paged_gather(q, kp, vp, table, pos, scale)


def paged_decode_attention(q, k_pages, v_pages, page_table, lengths,
                           scale=None, k_scales=None, v_scales=None,
                           tp=1):
    """Length-masked attention over one layer's page pool (raw arrays).

    q: (slots, s, heads, d); k_pages/v_pages: (num_pages, page_size,
    heads, d); page_table: (slots, max_pages) int32; lengths: (slots,)
    int32 — each slot's PRE-append valid length (the new rows were
    already scattered into the mapped pages, so query offset j attends
    keys t <= lengths + j; unmapped entries gather page 0 and are
    masked).  For the int8 pool, k_pages/v_pages are code arrays and
    ``k_scales/v_scales: (num_pages, page_size, heads)`` f32 select the
    q8 variants (dequantize inline in the gather).  ``tp`` records the
    tensor-parallel degree so the autotune key prices the PER-SHARD
    head count (trace-time shapes are global under jit-with-sharding).
    """
    from . import autotune as at
    kv_dtype = None if k_scales is None else k_pages.dtype
    key = paged_autotune_key(q.shape[0], k_pages.shape[0],
                             k_pages.shape[1], page_table.shape[1],
                             q.shape[2], q.shape[3], q.shape[1], q.dtype,
                             kv_dtype=kv_dtype, tp=tp)
    cand = at.resolve("decode_attn_paged", key)
    return _dispatch_paged(cand, q, k_pages, v_pages, page_table, lengths,
                           scale, k_scales=k_scales, v_scales=v_scales)


# ---------------------------------------------------------------------------
# autotune runner / traceable
# ---------------------------------------------------------------------------

_RUNNER_OPERANDS = {}


def _is_q8(key):
    """Quantized keys (int8 OR fp8/e4m3 — both route through the same
    codes+scales variants; the code dtype rides the key)."""
    return key.get("kv_dtype") in _QUANT_KV_DTYPES


def _key_kv_dtype(key):
    return jnp.dtype(key["kv_dtype"])


def _q8_synth(x, code_dtype):
    # synthetic runner/traceable operands quantize through the SAME grid
    # the serving cache writes with
    return quantize_kv(x, code_dtype)


def _operands(key):
    from ..core.dtype import x64_scope
    ks = tuple(sorted(key.items()))
    ops = _RUNNER_OPERANDS.get(ks)
    if ops is None:
        with x64_scope(False):
            rng = jax.random.key(0)
            kq, kk, kv = jax.random.split(rng, 3)
            dt = jnp.dtype(key["dtype"])
            b, t, h, d, s = (key["slots"], key["t"], key["h"], key["d"],
                            key["qlen"])
            q = jax.random.normal(kq, (b, s, h, d), dt)
            k = jax.random.normal(kk, (b, t, h, d), dt)
            v = jax.random.normal(kv, (b, t, h, d), dt)
            # representative fill: slots at staggered depths
            pos = (jnp.arange(b, dtype=jnp.int32) * (t // max(b, 1))
                   % jnp.asarray(max(t - s, 1), jnp.int32))
            scales = None
            if _is_q8(key):
                cdt = _key_kv_dtype(key)
                (k, ksc), (v, vsc) = _q8_synth(k, cdt), _q8_synth(v, cdt)
                scales = (ksc, vsc)
        ops = _RUNNER_OPERANDS[ks] = (q, k, v, pos, scales)
    return ops


def _runner(cand, key):
    from ..core.dtype import x64_scope
    q, k, v, pos, scales = _operands(key)
    kw = ({} if scales is None
          else {"k_scales": scales[0], "v_scales": scales[1]})
    with x64_scope(False):
        fn = jax.jit(functools.partial(_dispatch, cand, scale=None, **kw))
        fn(q, k, v, pos).block_until_ready()  # compile outside the timer

    def run():
        jax.block_until_ready(fn(q, k, v, pos))
    return run


def _cleanup(key):
    _RUNNER_OPERANDS.pop(tuple(sorted(key.items())), None)


def _traceable(cand, key):
    dt = jnp.dtype(key["dtype"])
    b, t, h, d, s = (key["slots"], key["t"], key["h"], key["d"],
                     key["qlen"])
    kv_dt = _key_kv_dtype(key) if _is_q8(key) else dt
    q = jax.ShapeDtypeStruct((b, s, h, d), dt)
    k = jax.ShapeDtypeStruct((b, t, h, d), kv_dt)
    v = jax.ShapeDtypeStruct((b, t, h, d), kv_dt)
    pos = jax.ShapeDtypeStruct((b,), jnp.int32)
    if _is_q8(key):
        sc = jax.ShapeDtypeStruct((b, t, h), jnp.float32)

        def fn(q, k, v, pos, ks, vs):
            return _dispatch(cand, q, k, v, pos, None,
                             k_scales=ks, v_scales=vs)
        return fn, (q, k, v, pos, sc, sc)
    return functools.partial(_dispatch, cand, scale=None), (q, k, v, pos)


def _paged_operands(key):
    from ..core.dtype import x64_scope
    ks = tuple(sorted(key.items()))
    ops = _RUNNER_OPERANDS.get(ks)
    if ops is None:
        with x64_scope(False):
            rng = jax.random.key(0)
            kq, kk, kv = jax.random.split(rng, 3)
            dt = jnp.dtype(key["dtype"])
            b, n_pages, P, mp, h, d, s = (
                key["slots"], key["pages"], key["page_size"],
                key["max_pages"], key["h"], key["d"], key["qlen"])
            q = jax.random.normal(kq, (b, s, h, d), dt)
            kp = jax.random.normal(kk, (n_pages, P, h, d), dt)
            vp = jax.random.normal(kv, (n_pages, P, h, d), dt)
            # representative mapping: round-robin over the pool, slots at
            # staggered fill depths (like the slotted runner's pos)
            table = (jnp.arange(b * mp, dtype=jnp.int32).reshape(b, mp)
                     % jnp.asarray(n_pages, jnp.int32))
            t = mp * P
            pos = (jnp.arange(b, dtype=jnp.int32) * (t // max(b, 1))
                   % jnp.asarray(max(t - s, 1), jnp.int32))
            scales = None
            if _is_q8(key):
                cdt = _key_kv_dtype(key)
                (kp, ksc), (vp, vsc) = (_q8_synth(kp, cdt),
                                        _q8_synth(vp, cdt))
                scales = (ksc, vsc)
        ops = _RUNNER_OPERANDS[ks] = (q, kp, vp, table, pos, scales)
    return ops


def _paged_runner(cand, key):
    from ..core.dtype import x64_scope
    q, kp, vp, table, pos, scales = _paged_operands(key)
    kw = ({} if scales is None
          else {"k_scales": scales[0], "v_scales": scales[1]})
    with x64_scope(False):
        fn = jax.jit(functools.partial(_dispatch_paged, cand, scale=None,
                                       **kw))
        fn(q, kp, vp, table, pos).block_until_ready()  # compile untimed

    def run():
        jax.block_until_ready(fn(q, kp, vp, table, pos))
    return run


def _paged_traceable(cand, key):
    dt = jnp.dtype(key["dtype"])
    b, n_pages, P, mp, h, d, s = (
        key["slots"], key["pages"], key["page_size"], key["max_pages"],
        key["h"], key["d"], key["qlen"])
    kv_dt = _key_kv_dtype(key) if _is_q8(key) else dt
    q = jax.ShapeDtypeStruct((b, s, h, d), dt)
    kp = jax.ShapeDtypeStruct((n_pages, P, h, d), kv_dt)
    vp = jax.ShapeDtypeStruct((n_pages, P, h, d), kv_dt)
    table = jax.ShapeDtypeStruct((b, mp), jnp.int32)
    pos = jax.ShapeDtypeStruct((b,), jnp.int32)
    if _is_q8(key):
        sc = jax.ShapeDtypeStruct((n_pages, P, h), jnp.float32)

        def fn(q, kp, vp, table, pos, ks, vs):
            return _dispatch_paged(cand, q, kp, vp, table, pos, None,
                                   k_scales=ks, v_scales=vs)
        return fn, (q, kp, vp, table, pos, sc, sc)
    return (functools.partial(_dispatch_paged, cand, scale=None),
            (q, kp, vp, table, pos))


def _register():
    from . import autotune as at
    at.register_family("decode_attn", _candidates, _runner,
                       cleanup=_cleanup, traceable=_traceable)
    at.register_family("decode_attn_paged", _paged_candidates,
                       _paged_runner, cleanup=_cleanup,
                       traceable=_paged_traceable)


_register()
