"""Decode attention — length-masked attention over the slotted KV cache.

The serving decode step attends ``q: (slots, s, heads, d)`` (``s`` is 1
for plain decode) against the full static cache ``k/v: (slots, max_len,
heads, d)`` with each slot masked to its valid prefix: query offset ``j``
of a slot with pre-append length ``n`` attends keys ``t <= n + j``.

Registered as the ``decode_attn`` autotune family so the variant choice
can be tuned on-chip next TPU session (PERF.md protocol).  Variants are
XLA-level (no Pallas) — at decode shapes the op is bandwidth-bound on
the K/V read, which XLA already streams well; what is worth tuning is
the *schedule*:

* ``masked`` (default) — one-shot: full ``(slots, h, s, max_len)``
  masked logits, f32 softmax statistics.  Minimal launches; peak memory
  O(slots*h*s*max_len) f32.
* ``chunked`` — online-softmax streamed over ``block_t``-sized key
  chunks (the flash recurrence along the time axis): O(block_t) logits
  working set, and chunks wholly past every slot's valid prefix still
  compute but contribute zeros.  Candidate win at long ``max_len`` where
  the one-shot logits buffer stops fitting close to the compute.

Both variants keep the bf16-region dtype discipline TPU501 audits:
``dot_general`` runs on the input dtype with ``preferred_element_type``
f32 accumulation, the softmax statistic chain stays f32, and ``p`` is
cast back to the input dtype before the second matmul.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["decode_attention", "autotune_key", "supported_block_ts"]

_NEG_INF = -1e30


def autotune_key(slots, t, h, d, qlen, dtype):
    from . import autotune as at
    return {"slots": int(slots), "t": int(t), "h": int(h), "d": int(d),
            "qlen": int(qlen), "dtype": str(jnp.dtype(dtype)),
            "platform": at.platform()}


def _scale(scale, d):
    if scale is None:
        return jnp.asarray(1.0 / (float(d) ** 0.5), jnp.float32)
    return jnp.asarray(scale, jnp.float32)


def _masked(q, k, v, pos, scale):
    """One-shot masked softmax attention (f32 statistics)."""
    s, t = q.shape[1], k.shape[1]
    # (B, s, H, D) x (B, T, H, D) -> (B, H, s, T), f32 accumulation
    logits = jnp.einsum("bqhd,bthd->bhqt", q, k,
                        preferred_element_type=jnp.float32)
    logits = logits * _scale(scale, q.shape[-1])
    t_ids = jnp.arange(t, dtype=jnp.int32)
    q_pos = pos[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
    valid = t_ids[None, None, None, :] <= q_pos[:, None, :, None]
    logits = jnp.where(valid, logits, jnp.asarray(_NEG_INF, jnp.float32))
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqt,bthd->bqhd", p, v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def _chunked(q, k, v, pos, scale, block_t):
    """Online-softmax over key chunks (flash recurrence along time)."""
    b, s, h, d = q.shape
    t = k.shape[1]
    n_chunks = t // block_t
    sc = _scale(scale, d)
    q_pos = pos[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
    kc = k.reshape(b, n_chunks, block_t, h, d)
    vc = v.reshape(b, n_chunks, block_t, h, d)
    # scan carries f32 statistics; chunks are the scanned axis
    kc = jnp.moveaxis(kc, 1, 0)
    vc = jnp.moveaxis(vc, 1, 0)

    def body(carry, xs):
        m, l, acc = carry
        k_blk, v_blk, c = xs
        logits = jnp.einsum("bqhd,bthd->bhqt", q, k_blk,
                            preferred_element_type=jnp.float32) * sc
        t_ids = c * block_t + jnp.arange(block_t, dtype=jnp.int32)
        valid = t_ids[None, None, None, :] <= q_pos[:, None, :, None]
        logits = jnp.where(valid, logits,
                           jnp.asarray(_NEG_INF, jnp.float32))
        m_blk = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        # m_new can stay -inf-ish for rows with no valid key yet; the
        # exp of (NEG_INF - NEG_INF) = exp(0) rows are zeroed by `valid`
        p = jnp.exp(logits - m_new[..., None])
        p = jnp.where(valid, p, jnp.zeros((), jnp.float32))
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqt,bthd->bhqd", p.astype(q.dtype), v_blk,
                        preferred_element_type=jnp.float32)
        acc_new = acc * corr[..., None] + pv
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((b, h, s), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s), jnp.float32)
    a0 = jnp.zeros((b, h, s, d), jnp.float32)
    chunk_ids = jnp.arange(n_chunks, dtype=jnp.int32)
    (m, l, acc), _ = jax.lax.scan(body, (m0, l0, a0), (kc, vc, chunk_ids))
    out = acc / jnp.maximum(l, jnp.asarray(1e-30, jnp.float32))[..., None]
    return jnp.moveaxis(out, 1, 2).astype(q.dtype)  # (B,H,s,D)->(B,s,H,D)


def supported_block_ts(t):
    return [bt for bt in (128, 256, 512) if t % bt == 0 and bt < t]


def _candidates(key):
    out = [{"variant": "masked", "config": {}}]
    for bt in supported_block_ts(key["t"]):
        out.append({"variant": "chunked", "config": {"block_t": bt}})
    return out


def _dispatch(cand, q, k, v, pos, scale):
    if cand.get("variant") == "chunked":
        bt = int(cand.get("config", {}).get("block_t", 0))
        if bt > 0 and k.shape[1] % bt == 0:
            return _chunked(q, k, v, pos, scale, bt)
        # invalid cached/pinned config for this key: fall back, never fault
    return _masked(q, k, v, pos, scale)


def decode_attention(q, k, v, lengths, scale=None):
    """Length-masked attention for the slotted decode step (raw arrays).

    q: (slots, s, heads, d); k/v: (slots, max_len, heads, d);
    lengths: (slots,) int32 — each slot's PRE-append valid length (the new
    rows were already written at [lengths, lengths+s), so query offset j
    attends keys t <= lengths + j).
    """
    from . import autotune as at
    key = autotune_key(q.shape[0], k.shape[1], q.shape[2], q.shape[3],
                       q.shape[1], q.dtype)
    cand = at.resolve("decode_attn", key)
    return _dispatch(cand, q, k, v, lengths, scale)


# ---------------------------------------------------------------------------
# autotune runner / traceable
# ---------------------------------------------------------------------------

_RUNNER_OPERANDS = {}


def _operands(key):
    from ..core.dtype import x64_scope
    ks = tuple(sorted(key.items()))
    ops = _RUNNER_OPERANDS.get(ks)
    if ops is None:
        with x64_scope(False):
            rng = jax.random.key(0)
            kq, kk, kv = jax.random.split(rng, 3)
            dt = jnp.dtype(key["dtype"])
            b, t, h, d, s = (key["slots"], key["t"], key["h"], key["d"],
                            key["qlen"])
            q = jax.random.normal(kq, (b, s, h, d), dt)
            k = jax.random.normal(kk, (b, t, h, d), dt)
            v = jax.random.normal(kv, (b, t, h, d), dt)
            # representative fill: slots at staggered depths
            pos = (jnp.arange(b, dtype=jnp.int32) * (t // max(b, 1))
                   % jnp.asarray(max(t - s, 1), jnp.int32))
        ops = _RUNNER_OPERANDS[ks] = (q, k, v, pos)
    return ops


def _runner(cand, key):
    from ..core.dtype import x64_scope
    q, k, v, pos = _operands(key)
    with x64_scope(False):
        fn = jax.jit(functools.partial(_dispatch, cand, scale=None))
        fn(q, k, v, pos).block_until_ready()  # compile outside the timer

    def run():
        jax.block_until_ready(fn(q, k, v, pos))
    return run


def _cleanup(key):
    _RUNNER_OPERANDS.pop(tuple(sorted(key.items())), None)


def _traceable(cand, key):
    dt = jnp.dtype(key["dtype"])
    b, t, h, d, s = (key["slots"], key["t"], key["h"], key["d"],
                     key["qlen"])
    q = jax.ShapeDtypeStruct((b, s, h, d), dt)
    k = jax.ShapeDtypeStruct((b, t, h, d), dt)
    v = jax.ShapeDtypeStruct((b, t, h, d), dt)
    pos = jax.ShapeDtypeStruct((b,), jnp.int32)
    return functools.partial(_dispatch, cand, scale=None), (q, k, v, pos)


def _register():
    from . import autotune as at
    at.register_family("decode_attn", _candidates, _runner,
                       cleanup=_cleanup, traceable=_traceable)


_register()
