"""paddle.batch — reader batching decorator (reference:
python/paddle/batch.py:18)."""
from __future__ import annotations

__all__ = ["batch"]


def batch(reader, batch_size, drop_last=False):
    """Wrap a sample generator into a mini-batch generator."""
    if batch_size <= 0:
        raise ValueError("batch_size should be a positive integer value, "
                         "but got batch_size={}".format(batch_size))

    def batch_reader():
        b = []
        for instance in reader():
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader
