"""paddle.linalg — namespace re-exports (reference: python/paddle/linalg.py,
a pure re-export of tensor.linalg).  The implementations live in
paddle_tpu.ops.linalg; this module mirrors the reference's import surface."""
from .ops import (cholesky, cholesky_solve, cond, cov, det, eig, eigh,
                  eigvals, eigvalsh, lstsq, lu, lu_unpack, matrix_power,
                  matrix_rank, multi_dot, norm, pinv, qr, slogdet, solve,
                  svd, triangular_solve)
from .ops import inverse as inv

__all__ = [
    "cholesky", "norm", "cond", "cov", "inv", "eig", "eigvals", "multi_dot",
    "matrix_rank", "svd", "qr", "lu", "lu_unpack", "matrix_power", "det",
    "slogdet", "eigh", "eigvalsh", "pinv", "solve", "cholesky_solve",
    "triangular_solve", "lstsq",
]
