// Shared-memory blocking ring queue for multiprocess data loading.
//
// Native C++ re-design of the reference's data-loader transport
// (paddle/fluid/framework/blocking_queue.h + the mmap'd shared-memory tensor
// path in python/paddle/fluid/dataloader/ + pybind/reader_py.cc queues):
// worker processes push pickled numpy batches into one shm ring buffer; the
// trainer process pops without a per-batch pipe/pickle copy through Python
// queues.  Process-shared pthread mutex/condvars in the shm header provide
// the blocking semantics.  C ABI + ctypes (no pybind11 in this image).
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <string>

namespace {

struct ShmHeader {
  pthread_mutex_t mu;
  pthread_cond_t not_empty;
  pthread_cond_t not_full;
  uint64_t capacity;   // data bytes
  uint64_t head;       // read offset
  uint64_t tail;       // write offset
  uint64_t used;       // bytes in use
  uint32_t closed;
  uint32_t n_items;
};

struct Queue {
  ShmHeader* hdr;
  char* data;
  uint64_t capacity;
  std::string name;
  bool owner;
};

// each item: u64 length | payload (contiguous logical ring)
void ring_write(Queue* q, const char* src, uint64_t n) {
  uint64_t t = q->hdr->tail;
  uint64_t first = std::min(n, q->capacity - t);
  std::memcpy(q->data + t, src, first);
  if (n > first) std::memcpy(q->data, src + first, n - first);
  q->hdr->tail = (t + n) % q->capacity;
}

void ring_read(Queue* q, char* dst, uint64_t n) {
  uint64_t h = q->hdr->head;
  uint64_t first = std::min(n, q->capacity - h);
  std::memcpy(dst, q->data + h, first);
  if (n > first) std::memcpy(dst + first, q->data, n - first);
  q->hdr->head = (h + n) % q->capacity;
}

}  // namespace

extern "C" {

void* shm_queue_create(const char* name, long long capacity) {
  ::shm_unlink(name);
  int fd = ::shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  uint64_t total = sizeof(ShmHeader) + static_cast<uint64_t>(capacity);
  if (::ftruncate(fd, static_cast<off_t>(total)) != 0) {
    ::close(fd);
    ::shm_unlink(name);
    return nullptr;
  }
  void* mem = ::mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* hdr = static_cast<ShmHeader*>(mem);
  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hdr->mu, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&hdr->not_empty, &ca);
  pthread_cond_init(&hdr->not_full, &ca);
  hdr->capacity = static_cast<uint64_t>(capacity);
  hdr->head = hdr->tail = hdr->used = 0;
  hdr->closed = 0;
  hdr->n_items = 0;
  auto* q = new Queue{hdr, reinterpret_cast<char*>(mem) + sizeof(ShmHeader),
                      hdr->capacity, name, true};
  return q;
}

void* shm_queue_open(const char* name) {
  int fd = ::shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (::fstat(fd, &st) != 0) {
    ::close(fd);
    return nullptr;
  }
  void* mem = ::mmap(nullptr, static_cast<size_t>(st.st_size),
                     PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* hdr = static_cast<ShmHeader*>(mem);
  auto* q = new Queue{hdr, reinterpret_cast<char*>(mem) + sizeof(ShmHeader),
                      hdr->capacity, name, false};
  return q;
}

static int lock_robust(ShmHeader* hdr) {
  int rc = pthread_mutex_lock(&hdr->mu);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&hdr->mu);
    rc = 0;
  }
  return rc;
}

// push: blocks until space; returns 0 ok, -1 closed/error
int shm_queue_push(void* queue, const char* buf, long long len) {
  auto* q = static_cast<Queue*>(queue);
  auto* hdr = q->hdr;
  uint64_t need = 8 + static_cast<uint64_t>(len);
  if (need > q->capacity) return -2;
  if (lock_robust(hdr) != 0) return -1;
  while (hdr->capacity - hdr->used < need && !hdr->closed)
    pthread_cond_wait(&hdr->not_full, &hdr->mu);
  if (hdr->closed) {
    pthread_mutex_unlock(&hdr->mu);
    return -1;
  }
  uint64_t n = static_cast<uint64_t>(len);
  ring_write(q, reinterpret_cast<const char*>(&n), 8);
  ring_write(q, buf, n);
  hdr->used += need;
  hdr->n_items += 1;
  pthread_cond_signal(&hdr->not_empty);
  pthread_mutex_unlock(&hdr->mu);
  return 0;
}

// pop: blocks; returns item length (caller buffer must be >= cap) or
// -1 closed+empty, -3 cap too small (item left in queue)
long long shm_queue_pop(void* queue, char* out, long long cap) {
  auto* q = static_cast<Queue*>(queue);
  auto* hdr = q->hdr;
  if (lock_robust(hdr) != 0) return -1;
  while (hdr->n_items == 0 && !hdr->closed)
    pthread_cond_wait(&hdr->not_empty, &hdr->mu);
  if (hdr->n_items == 0 && hdr->closed) {
    pthread_mutex_unlock(&hdr->mu);
    return -1;
  }
  uint64_t n;
  uint64_t save_head = hdr->head;
  ring_read(q, reinterpret_cast<char*>(&n), 8);
  if (static_cast<long long>(n) > cap) {
    hdr->head = save_head;  // put back
    pthread_mutex_unlock(&hdr->mu);
    return -3;
  }
  ring_read(q, out, n);
  hdr->used -= (8 + n);
  hdr->n_items -= 1;
  pthread_cond_signal(&hdr->not_full);
  pthread_mutex_unlock(&hdr->mu);
  return static_cast<long long>(n);
}

long long shm_queue_size(void* queue) {
  auto* q = static_cast<Queue*>(queue);
  lock_robust(q->hdr);
  long long n = q->hdr->n_items;
  pthread_mutex_unlock(&q->hdr->mu);
  return n;
}

void shm_queue_close(void* queue) {
  auto* q = static_cast<Queue*>(queue);
  lock_robust(q->hdr);
  q->hdr->closed = 1;
  pthread_cond_broadcast(&q->hdr->not_empty);
  pthread_cond_broadcast(&q->hdr->not_full);
  pthread_mutex_unlock(&q->hdr->mu);
}

void shm_queue_destroy(void* queue) {
  auto* q = static_cast<Queue*>(queue);
  uint64_t total = sizeof(ShmHeader) + q->capacity;
  bool owner = q->owner;
  std::string name = q->name;
  ::munmap(q->hdr, total);
  if (owner) ::shm_unlink(name.c_str());
  delete q;
}

}  // extern "C"
