// TCPStore — key/value rendezvous server + client.
//
// Native C++ re-design of the reference's rendezvous store
// (paddle/fluid/distributed/store/tcp_store.{h,cc}:91 TCPStore/MasterDaemon):
// the master rank listens, peers connect over TCP and issue SET/GET/ADD/WAIT.
// Used by paddle_tpu.distributed bootstrap when jax.distributed's built-in
// coordination is unavailable (and by tests as the multi-process sync
// primitive).  Exposed to Python via a plain C ABI + ctypes (no pybind11 in
// this image).
//
// Wire format (little-endian):
//   u8 op  | u32 keylen | key bytes | (SET/ADD: u32 vallen | val bytes)
// ops: 1=SET 2=GET 3=ADD 4=WAIT 5=DELETE 6=NUMKEYS
// replies: GET/WAIT -> u32 len | bytes (len==0xFFFFFFFF => missing)
//          ADD -> i64 new value; SET/DELETE -> u8 ack; NUMKEYS -> u32
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

enum Op : uint8_t { SET = 1, GET = 2, ADD = 3, WAIT = 4, DEL = 5, NUM = 6 };

bool read_full(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  size_t got = 0;
  while (got < n) {
    ssize_t r = ::recv(fd, p + got, n - got, 0);
    if (r <= 0) return false;
    got += static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  size_t sent = 0;
  while (sent < n) {
    ssize_t r = ::send(fd, p + sent, n - sent, 0);
    if (r <= 0) return false;
    sent += static_cast<size_t>(r);
  }
  return true;
}

class StoreServer {
 public:
  explicit StoreServer(int port) : port_(port), stop_(false) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
        ::listen(listen_fd_, 128) != 0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return;
    }
    if (port == 0) {
      socklen_t len = sizeof(addr);
      ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
      port_ = ntohs(addr.sin_port);
    }
    accept_thread_ = std::thread([this] { AcceptLoop(); });
  }

  ~StoreServer() { Stop(); }

  bool ok() const { return listen_fd_ >= 0; }
  int port() const { return port_; }

  void Stop() {
    bool expected = false;
    if (!stop_.compare_exchange_strong(expected, true)) return;
    if (listen_fd_ >= 0) ::shutdown(listen_fd_, SHUT_RDWR), ::close(listen_fd_);
    if (accept_thread_.joinable()) accept_thread_.join();
    // Wake WAIT-blocked threads (their predicate checks stop_) and unblock
    // recv-blocked threads by shutting down every live connection; only then
    // is join guaranteed to complete even with clients still attached.
    {
      // mu_ orders the stop_ store with a waiter between its predicate
      // check and blocking — notify without it can be lost.
      std::lock_guard<std::mutex> lk(mu_);
    }
    cv_.notify_all();
    std::vector<std::thread> threads;
    {
      std::lock_guard<std::mutex> lk(conn_mu_);
      for (int fd : conn_fds_)
        if (fd >= 0) ::shutdown(fd, SHUT_RDWR);
      threads.swap(conn_threads_);
    }
    // join with conn_mu_ released: Serve()'s fd cleanup takes conn_mu_.
    for (auto& t : threads)
      if (t.joinable()) t.join();
  }

 private:
  void AcceptLoop() {
    while (!stop_) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) break;
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> lk(conn_mu_);
      // reap threads whose connections already closed (their fd slot was
      // tombstoned in Serve) so long-lived servers don't accumulate one
      // dead std::thread + fd slot per connection ever accepted
      for (size_t i = 0; i < conn_fds_.size();) {
        if (conn_fds_[i] < 0) {
          if (conn_threads_[i].joinable()) conn_threads_[i].join();
          conn_fds_[i] = conn_fds_.back();
          conn_fds_.pop_back();
          std::swap(conn_threads_[i], conn_threads_.back());
          conn_threads_.pop_back();
        } else {
          ++i;
        }
      }
      conn_fds_.push_back(fd);
      conn_threads_.emplace_back([this, fd] { Serve(fd); });
    }
  }

  void Serve(int fd) {
    while (!stop_) {
      uint8_t op;
      if (!read_full(fd, &op, 1)) break;
      uint32_t klen;
      if (!read_full(fd, &klen, 4)) break;
      std::string key(klen, '\0');
      if (klen && !read_full(fd, key.data(), klen)) break;
      if (op == SET) {
        uint32_t vlen;
        if (!read_full(fd, &vlen, 4)) break;
        std::string val(vlen, '\0');
        if (vlen && !read_full(fd, val.data(), vlen)) break;
        {
          std::lock_guard<std::mutex> lk(mu_);
          data_[key] = std::move(val);
        }
        cv_.notify_all();
        uint8_t ack = 1;
        if (!write_full(fd, &ack, 1)) break;
      } else if (op == GET) {
        std::string val;
        bool found;
        {
          std::lock_guard<std::mutex> lk(mu_);
          auto it = data_.find(key);
          found = it != data_.end();
          if (found) val = it->second;
        }
        uint32_t len = found ? static_cast<uint32_t>(val.size()) : 0xFFFFFFFFu;
        if (!write_full(fd, &len, 4)) break;
        if (found && !val.empty() && !write_full(fd, val.data(), val.size()))
          break;
      } else if (op == ADD) {
        uint32_t vlen;
        if (!read_full(fd, &vlen, 4)) break;
        std::string val(vlen, '\0');
        if (vlen && !read_full(fd, val.data(), vlen)) break;
        int64_t inc = 0;
        std::memcpy(&inc, val.data(), std::min<size_t>(8, val.size()));
        int64_t out;
        {
          std::lock_guard<std::mutex> lk(mu_);
          int64_t cur = 0;
          auto it = data_.find(key);
          if (it != data_.end())
            std::memcpy(&cur, it->second.data(),
                        std::min<size_t>(8, it->second.size()));
          out = cur + inc;
          std::string nv(8, '\0');
          std::memcpy(nv.data(), &out, 8);
          data_[key] = nv;
        }
        cv_.notify_all();
        if (!write_full(fd, &out, 8)) break;
      } else if (op == WAIT) {
        std::string val;
        {
          std::unique_lock<std::mutex> lk(mu_);
          cv_.wait(lk, [&] {
            return stop_.load() || data_.count(key) > 0;
          });
          if (stop_) break;
          val = data_[key];
        }
        uint32_t len = static_cast<uint32_t>(val.size());
        if (!write_full(fd, &len, 4)) break;
        if (!val.empty() && !write_full(fd, val.data(), val.size())) break;
      } else if (op == DEL) {
        {
          std::lock_guard<std::mutex> lk(mu_);
          data_.erase(key);
        }
        uint8_t ack = 1;
        if (!write_full(fd, &ack, 1)) break;
      } else if (op == NUM) {
        uint32_t n;
        {
          std::lock_guard<std::mutex> lk(mu_);
          n = static_cast<uint32_t>(data_.size());
        }
        if (!write_full(fd, &n, 4)) break;
      } else {
        break;
      }
    }
    {
      std::lock_guard<std::mutex> lk(conn_mu_);
      for (auto& f : conn_fds_)
        if (f == fd) f = -1;
    }
    ::close(fd);
  }

  int listen_fd_ = -1;
  int port_;
  std::atomic<bool> stop_;
  std::thread accept_thread_;
  std::mutex conn_mu_;
  std::vector<int> conn_fds_;
  std::vector<std::thread> conn_threads_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::map<std::string, std::string> data_;
};

class StoreClient {
 public:
  StoreClient(const char* host, int port, int timeout_ms = 30000) {
    fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    ::inet_pton(AF_INET, host, &addr.sin_addr);
    // retry connect until the deadline (server may start later); at
    // least one attempt even for timeout_ms <= 0
    int attempts = timeout_ms / 100 + 1;
    for (int i = 0; i < attempts; i++) {
      if (::connect(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
        int one = 1;
        ::setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        ok_ = true;
        return;
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(100));
    }
    ::close(fd_);
    fd_ = -1;
  }

  ~StoreClient() {
    if (fd_ >= 0) ::close(fd_);
  }

  bool ok() const { return ok_; }

  bool Set(const std::string& key, const std::string& val) {
    std::lock_guard<std::mutex> lk(mu_);
    if (!SendHeader(SET, key)) return false;
    uint32_t vlen = static_cast<uint32_t>(val.size());
    if (!write_full(fd_, &vlen, 4)) return false;
    if (!val.empty() && !write_full(fd_, val.data(), val.size())) return false;
    uint8_t ack;
    return read_full(fd_, &ack, 1);
  }

  // returns -1 missing, else value length written into out (truncated to cap)
  int64_t Get(const std::string& key, char* out, int64_t cap, bool wait) {
    std::lock_guard<std::mutex> lk(mu_);
    if (!SendHeader(wait ? WAIT : GET, key)) return -2;
    uint32_t len;
    if (!read_full(fd_, &len, 4)) return -2;
    if (len == 0xFFFFFFFFu) return -1;
    std::string val(len, '\0');
    if (len && !read_full(fd_, val.data(), len)) return -2;
    int64_t n = std::min<int64_t>(len, cap);
    std::memcpy(out, val.data(), static_cast<size_t>(n));
    return static_cast<int64_t>(len);
  }

  int64_t Add(const std::string& key, int64_t inc) {
    std::lock_guard<std::mutex> lk(mu_);
    if (!SendHeader(ADD, key)) return INT64_MIN;
    uint32_t vlen = 8;
    if (!write_full(fd_, &vlen, 4)) return INT64_MIN;
    if (!write_full(fd_, &inc, 8)) return INT64_MIN;
    int64_t out;
    if (!read_full(fd_, &out, 8)) return INT64_MIN;
    return out;
  }

 private:
  bool SendHeader(Op op, const std::string& key) {
    uint8_t o = op;
    if (!write_full(fd_, &o, 1)) return false;
    uint32_t klen = static_cast<uint32_t>(key.size());
    if (!write_full(fd_, &klen, 4)) return false;
    return key.empty() || write_full(fd_, key.data(), key.size());
  }

  int fd_ = -1;
  bool ok_ = false;
  std::mutex mu_;
};

}  // namespace

extern "C" {

void* tcp_store_server_create(int port) {
  auto* s = new StoreServer(port);
  if (!s->ok()) {
    delete s;
    return nullptr;
  }
  return s;
}

int tcp_store_server_port(void* server) {
  return static_cast<StoreServer*>(server)->port();
}

void tcp_store_server_destroy(void* server) {
  delete static_cast<StoreServer*>(server);
}

void* tcp_store_client_create_t(const char* host, int port, int timeout_ms) {
  auto* c = new StoreClient(host, port, timeout_ms);
  if (!c->ok()) {
    delete c;
    return nullptr;
  }
  return c;
}

void* tcp_store_client_create(const char* host, int port) {
  return tcp_store_client_create_t(host, port, 30000);
}

void tcp_store_client_destroy(void* client) {
  delete static_cast<StoreClient*>(client);
}

int tcp_store_set(void* client, const char* key, const char* val, int len) {
  return static_cast<StoreClient*>(client)->Set(key, std::string(val, len)) ? 0
                                                                            : -1;
}

long long tcp_store_get(void* client, const char* key, char* out,
                        long long cap, int wait) {
  return static_cast<StoreClient*>(client)->Get(key, out, cap, wait != 0);
}

long long tcp_store_add(void* client, const char* key, long long inc) {
  return static_cast<StoreClient*>(client)->Add(key, inc);
}

}  // extern "C"
