"""Benchmark: goodput-vs-QPS through the LIVE async serving front-end.

The load harness half of ISSUE 13: starts the real HTTP front-end
(`paddle_tpu.serving.frontend`) over the compiled engine, offers Poisson
traffic at each requested QPS over a prompt/output length mix
(`paddle_tpu.serving.loadgen`), and prints ONE JSON line per (QPS, mix)
— the ``BENCH_serve_*`` trajectory format::

  {"metric": "serve_goodput_tokens_per_sec", "value": N, "unit": "tok/s",
   "qps": ..., "mix": ..., "ttft_p50_ms": ..., "ttft_p99_ms": ...,
   "tpot_p50_ms": ..., "tpot_p99_ms": ..., "shed_rate": ...,
   "cache_layout": ..., "kv_dtype": ..., "spec": ..., "tp": ...,
   "overlap": ..., "disagg": ..., "metrics": {...}, "config": {...}}

Every field the decode trajectory cursors key on rides along, plus the
serve axes (qps, mix, overlap, disagg): ``tools/bench_schema.py
--trajectory`` gates serve lines like-for-like — >3% goodput drop OR
>3% p99-TTFT growth between consecutive on-chip entries fails; CPU
lines are smoke and never perf-gate.  TTFT/TPOT here are measured at
the CLIENT (first delivered SSE token), so queueing, HTTP framing, and
the scheduler thread handoff are all inside the number — the p99 is
what a user would see, not what the engine dispatched.

**Disaggregated prefill/decode (ISSUE 15).**  ``--disagg on`` serves
through role-split engines — a prefill engine (pinned to its own device
when the backend has >= 2) hands finished KV off to the decode engine
page-chunk by page-chunk (``serving/disagg.py``); its lines carry
``"disagg": true`` plus the per-point ``handoff_bytes``/``handoffs``
and the ``serving.handoff_seconds`` histogram.  ``--disagg ab`` runs
the colocated arm then the disagg arm over the SAME seeded workload and
emits both lines.  ``--wave N`` replaces the plain load with the
interference drive (``loadgen.run_interference``): a steady stream of
``--mix`` requests plus a concurrent N-request ``prefill_heavy``
admission wave; the line's ``wave`` block reports steady-stream
inter-token p50/p99 split into quiet-vs-wave windows — the decode-TPOT
isolation headline.  ``--ab-assert`` (the CI gate) requires, with
``--disagg ab --wave N``, that the wave measurably inflates the
colocated baseline's in-flight p99 TPOT while the disagg arm inflates
strictly less.

**Replicated fleet (ISSUE 19).**  ``--replicas N`` serves through the
router tier (``serving/router.py``): N data-parallel scheduler+engine
replicas behind ONE front-end, prefix-affinity + least-loaded routing,
health-probed.  Lines carry ``"replicas": N`` (a trajectory cursor
axis, so fleet series never compare against single-replica history)
and the compile-once gate scales to N — each replica compiles each
watched program exactly once.  ``--kill-replica`` arms the chaos line:
a ``serve.replica`` HardExit kills one replica mid-drive, its in-flight
streams requeue onto survivors, and the line hard-asserts
``dropped_streams == 0`` and ``router.failovers >= 1`` — failover must
resume streams, not drop them.  The wall-clock fleet-vs-single numbers
only gate on a TPU backend (CPU replicas share host cores; same
discipline as every other arm).

The engine runs the OVERLAPPED decode loop (``--overlap off`` for the
sync A/B) under the STRICT recompile watchdog: the decode program must
compile exactly once across the whole sweep — admission churn, shed
bursts, mid-stream disconnects, handoffs, replica failovers and all
(the schema gate re-checks the reported count; disagg arms also report
``serving.kv_export``/``serving.kv_import`` at exactly 1).

On TPU: GPT-2 345M at serving shapes.  On CPU: the tiny head_dim-64
smoke config (numbers are smoke; the line carries backend so the gate
knows).  Knobs: PADDLE_TPU_BENCH_SLOTS / _REQUESTS.
"""
from __future__ import annotations

import argparse
import gc
import json
import os
import sys
import time


def main(argv=None):
    os.environ.setdefault("PADDLE_TPU_STRICT_COMPILE", "1")
    ap = argparse.ArgumentParser(
        prog="python bench_serve.py",
        description="serving front-end load benchmark (goodput vs QPS)")
    ap.add_argument("--qps", default="4,16",
                    help="comma list of offered Poisson rates (one "
                         "BENCH_serve line each)")
    ap.add_argument("--mix", default="short",
                    help="prompt/output length mix name (serving."
                         "loadgen.MIXES: short|mixed|long|"
                         "prefill_heavy|decode_heavy)")
    ap.add_argument("--requests", type=int, default=None,
                    help="requests per QPS point (default 12 CPU / 32 "
                         "TPU; PADDLE_TPU_BENCH_REQUESTS overrides)")
    ap.add_argument("--queue-limit", type=int, default=32,
                    help="front-end admission bound (shed with 429 "
                         "above it)")
    ap.add_argument("--overlap", default="on", choices=("on", "off"),
                    help="overlapped host/device decode loop (off = the "
                         "sync A/B baseline)")
    ap.add_argument("--kv-dtype", default="bf16", choices=("bf16", "int8"))
    ap.add_argument("--spec", default="off",
                    help="'off' or a speculative draft length k")
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel degree (needs tp devices)")
    ap.add_argument("--disagg", default="off", choices=("off", "on", "ab"),
                    help="role-split prefill/decode serving; 'ab' runs "
                         "the colocated arm then the disagg arm over "
                         "the same seeded workload (one line each)")
    ap.add_argument("--wave", type=int, default=0, metavar="N",
                    help="interference drive: N concurrent prefill_heavy"
                         " admissions mid-stream; the line gains a "
                         "'wave' block with quiet-vs-wave in-flight "
                         "TPOT percentiles")
    ap.add_argument("--wave-repeats", type=int, default=1, metavar="K",
                    help="repeat the steady+wave cycle K times and pool "
                         "the gap samples (a one-cycle wave-window p99 "
                         "is ~the max of the set; K=3 makes the "
                         "isolation gate CI-stable)")
    ap.add_argument("--ab-assert", action="store_true",
                    help="with --disagg ab --wave N: the isolation "
                         "gate.  Always asserts STRUCTURAL isolation "
                         "(both arms measured wave-window gaps; the "
                         "disagg arm handed off and its decode engine "
                         "never compiled/ran a prefill program — "
                         "prefill compute cannot touch the decode "
                         "role).  On a TPU backend it additionally "
                         "asserts the wall-clock headline: the wave "
                         "degrades the colocated arm's in-flight p99 "
                         "TPOT (> 1.05x) and the disagg arm degrades "
                         "strictly less.  CPU hosts report the same "
                         "numbers but never perf-gate on them (CI "
                         "runners share cores across the virtual "
                         "devices, so wall-clock isolation there is "
                         "scheduling noise — the bench_schema "
                         "trajectory discipline).  Needs >= 2 devices.")
    ap.add_argument("--replicas", type=int, default=1, metavar="N",
                    help="serve through the router tier over N "
                         "data-parallel replicas (1 = classic "
                         "single-scheduler front-end)")
    ap.add_argument("--kill-replica", action="store_true",
                    help="chaos arm (needs --replicas >= 2): HardExit "
                         "one replica mid-drive at every QPS point; "
                         "hard-asserts dropped_streams == 0 and "
                         "router.failovers >= 1")
    ap.add_argument("--kill-at", type=int, default=20, metavar="K",
                    help="replica-loop iteration index (across the "
                         "fleet) at which the kill fires")
    ap.add_argument("--trace-file", default=None, metavar="PATH",
                    help="export the request-scoped span trace (JSONL) "
                         "of the LAST QPS point's drive")
    args = ap.parse_args(argv)

    import jax
    import numpy as np  # noqa: F401  (kept for parity with bench_decode)

    import paddle_tpu as paddle
    from paddle_tpu import observability as obs
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.observability import flight as _flight
    from paddle_tpu.observability import tracing as _tracing
    from paddle_tpu.observability import watchdog as _wd
    from paddle_tpu.robustness import faultpoints as fp
    from paddle_tpu.serving import loadgen
    from paddle_tpu.serving.engine import DecodeEngine
    from paddle_tpu.serving.frontend import ServingFrontend
    from paddle_tpu.serving.router import Router
    from paddle_tpu.serving.scheduler import (
        ContinuousBatchingScheduler, Request)

    spec = 0 if args.spec in ("off", "0") else int(args.spec)
    overlap = args.overlap == "on"
    on_tpu = jax.default_backend() == "tpu"
    devices = jax.devices()
    if args.tp > len(devices):
        raise SystemExit(
            "bench_serve: --tp %d needs %d devices, have %d (CPU: set "
            "XLA_FLAGS=--xla_force_host_platform_device_count)"
            % (args.tp, args.tp, len(devices)))
    if args.disagg != "off" and args.tp > 1:
        raise SystemExit("bench_serve: --disagg composes with tp on the "
                         "decode side only; run --tp separately")
    if args.ab_assert and (args.disagg != "ab" or not args.wave):
        raise SystemExit("bench_serve: --ab-assert needs --disagg ab "
                         "and --wave N")
    if args.replicas < 1:
        raise SystemExit("bench_serve: --replicas must be >= 1")
    if args.replicas > 1 and (args.disagg != "off" or args.tp > 1
                              or args.wave):
        raise SystemExit("bench_serve: --replicas composes with none of "
                         "--disagg/--tp/--wave yet — data-parallel "
                         "replicas are whole serving stacks; run those "
                         "axes per-replica in their own sweeps")
    if args.kill_replica and args.replicas < 2:
        raise SystemExit("bench_serve: --kill-replica needs "
                         "--replicas >= 2 (a failover needs a survivor)")
    if args.ab_assert and len(devices) < 2:
        raise SystemExit(
            "bench_serve: --ab-assert needs >= 2 devices so the prefill "
            "engine gets its own chip (CPU: set XLA_FLAGS="
            "--xla_force_host_platform_device_count) — on one device "
            "the roles share compute and isolation cannot show")
    paddle.seed(0)
    if on_tpu:
        cfg = GPTConfig.gpt2_medium()
        model_name = "gpt2_345m"
        num_slots, requests, max_len, page_size = 8, 32, 1024, 64
    else:
        cfg = GPTConfig(vocab_size=512, max_position_embeddings=256,
                        hidden_size=128, num_hidden_layers=2,
                        num_attention_heads=2, intermediate_size=256)
        model_name = "tiny_d64"
        num_slots, requests, max_len, page_size = 4, 12, 128, 16
    num_slots = int(os.getenv("PADDLE_TPU_BENCH_SLOTS", num_slots))
    requests = int(args.requests if args.requests is not None
                   else os.getenv("PADDLE_TPU_BENCH_REQUESTS", requests))
    cfg.hidden_dropout_prob = cfg.attention_dropout_prob = 0.0
    model = GPTForCausalLM(cfg)
    if on_tpu:
        paddle.amp.decorate(model, level="O2", dtype="bfloat16")
    model.eval()

    qps_list = [float(t) for t in str(args.qps).split(",") if t.strip()]
    kv_dtype = "int8" if args.kv_dtype == "int8" else None

    def run_arm(disagg):
        """One sweep (all QPS points) through a fresh front-end; emits
        one schema'd line per point and returns the arm's wave block +
        isolation accounting."""
        # drop the previous arm's engines: the watchdog's
        # compile_counts() sums over LIVE same-name entries, and the
        # jitted closures hold reference cycles that outlive run_arm
        gc.collect()
        tracer = _tracing.Tracer() if args.trace_file else None
        if disagg and len(devices) >= 2:
            # role split across devices: decode on 0, prefill on 1 —
            # the whole point of the architecture (one device = smoke
            # only: roles share compute and isolation cannot show)
            decode_dev, prefill_dev = devices[0], devices[1]
        else:
            decode_dev = prefill_dev = None
        router = None
        prefill_engine = None
        if args.replicas > 1:
            engines = [DecodeEngine(model, num_slots=num_slots,
                                    max_len=max_len, seed=0,
                                    page_size=page_size,
                                    kv_dtype=kv_dtype, spec_k=spec,
                                    tracer=tracer, tp=args.tp)
                       for _ in range(args.replicas)]
            engine = engines[0]
            # deterministic per-replica warmup: routing is load-shaped,
            # so an HTTP warmup drive cannot GUARANTEE every replica
            # compiles before the measured (strict-watchdog, compile-
            # once-gated) points — drive each engine directly instead;
            # the compiled programs are engine-owned and survive into
            # the router's own schedulers
            for eng in engines:
                s = ContinuousBatchingScheduler(eng, overlap=overlap)
                s.submit(Request(
                    prompt=np.arange(1, page_size + 1, dtype=np.int32),
                    max_new_tokens=4, temperature=0.0))
                while s.has_work():
                    s.step()
            router = Router(engines, tracer=tracer, overlap=overlap,
                            respawn_delay=0.1, healthy_interval=0.5)
            fe = ServingFrontend(router=router,
                                 queue_limit=args.queue_limit,
                                 tracer=tracer)
        else:
            engine = DecodeEngine(model, num_slots=num_slots,
                                  max_len=max_len, seed=0,
                                  page_size=page_size, kv_dtype=kv_dtype,
                                  spec_k=spec, tracer=tracer, tp=args.tp,
                                  device=decode_dev)
            if disagg:
                prefill_engine = DecodeEngine(
                    model, num_slots=max(2, num_slots // 2),
                    max_len=max_len, seed=0, page_size=page_size,
                    kv_dtype=kv_dtype, tracer=tracer, device=prefill_dev)
            fe = ServingFrontend(engine, queue_limit=args.queue_limit,
                                 overlap=overlap, tracer=tracer,
                                 prefill_engine=prefill_engine)
        host, port = fe.start()
        last_wave = None

        def fleet_gap_steps():
            scheds = [r.scheduler for r in router.replicas]
            return (sum(s.host_gap_seconds for s in scheds),
                    sum(s.decode_steps_total for s in scheds))

        try:
            # warmup drive: compiles prefill + decode (+ handoff) once
            # (fleet replicas were warmed deterministically above; this
            # warms the HTTP/admission path)
            loadgen.run_load_sync(host, port, qps=max(qps_list),
                                  n_requests=2, mix=args.mix, seed=99,
                                  vocab=cfg.vocab_size)
            for qps in qps_list:
                # percentiles must describe THIS point's drive (reset
                # ordering per OBSERVABILITY.md: flight snapshot first,
                # then registry reset, then watchdog shadow resync)
                _flight.note_registry_reset()
                obs.default_registry().reset()
                _wd.resync_counter()
                if tracer is not None:
                    tracer.reset()
                sched = fe.scheduler
                if router is not None:
                    gap0, steps0 = fleet_gap_steps()
                    ho_bytes0 = ho_n0 = 0
                else:
                    gap0 = sched.host_gap_seconds
                    steps0 = sched.decode_steps_total
                    ho_bytes0 = getattr(sched, "handoff_bytes_total", 0)
                    ho_n0 = getattr(sched, "handoffs_total", 0)
                plan = None
                if args.kill_replica:
                    # the chaos plan is scoped to the MEASURED drive
                    # only (the warmup fires the same site); the kill
                    # lands a few fleet-loop iterations in, while
                    # streams are in flight
                    plan = fp.FaultPlan()
                    plan.inject("serve.replica", fp.HardExit(),
                                at=args.kill_at)
                if args.wave:
                    summary = loadgen.run_interference_sync(
                        host, port, qps=qps, n_requests=requests,
                        mix=args.mix, wave_n=args.wave, seed=0,
                        vocab=cfg.vocab_size,
                        repeats=args.wave_repeats)
                elif plan is not None:
                    with fp.chaos(plan):
                        summary = loadgen.run_load_sync(
                            host, port, qps=qps, n_requests=requests,
                            mix=args.mix, seed=0, vocab=cfg.vocab_size)
                    plan.assert_all_fired()
                else:
                    summary = loadgen.run_load_sync(
                        host, port, qps=qps, n_requests=requests,
                        mix=args.mix, seed=0, vocab=cfg.vocab_size)
                failovers = (int(obs.counter("router.failovers").value)
                             if router is not None else 0)
                if plan is not None:
                    # the killed replica must respawn and rejoin before
                    # the next point measures a degraded fleet
                    deadline = time.monotonic() + 10.0
                    while (router.healthy_count() < args.replicas
                           and time.monotonic() < deadline):
                        time.sleep(0.05)
                    if router.healthy_count() < args.replicas:
                        raise SystemExit(
                            "bench_serve: killed replica did not rejoin "
                            "within 10s (states %r)"
                            % (router.replica_states(),))

                def _pcts(name):
                    h = obs.histogram(name)
                    return {"p50_ms": round(1e3 * h.percentile(0.50), 3),
                            "p95_ms": round(1e3 * h.percentile(0.95), 3),
                            "p99_ms": round(1e3 * h.percentile(0.99), 3),
                            "count": h.count}

                hists = {
                    "serving.ttft_seconds":
                        _pcts("serving.ttft_seconds"),
                    "serving.tpot_seconds":
                        _pcts("serving.tpot_seconds"),
                    "serving.queue_wait_seconds":
                        _pcts("serving.queue_wait_seconds"),
                    "serving.decode_step_seconds":
                        _pcts("serving.decode_step_seconds"),
                }
                if disagg:
                    hists["serving.handoff_seconds"] = \
                        _pcts("serving.handoff_seconds")
                line = {
                    "metric": "serve_goodput_tokens_per_sec",
                    "value": summary["goodput_tokens_per_sec"],
                    "unit": "tok/s",
                    # the serve trajectory cursor axes (bench_schema
                    # keys series on model+layout+kv+spec+tp+overlap+
                    # disagg+qps+mix)
                    "qps": summary["qps"],
                    "mix": summary["mix"],
                    "cache_layout": "paged",
                    "kv_dtype": args.kv_dtype,
                    "spec": spec,
                    "tp": args.tp,
                    "overlap": overlap,
                    "disagg": bool(disagg),
                    "replicas": args.replicas,
                    # client-observed latency (the acceptance numbers)
                    "ttft_p50_ms": summary["ttft_p50_ms"],
                    "ttft_p99_ms": summary["ttft_p99_ms"],
                    "tpot_p50_ms": summary["tpot_p50_ms"],
                    "tpot_p99_ms": summary["tpot_p99_ms"],
                    "shed_rate": summary["shed_rate"],
                    "sent": summary["sent"],
                    "completed": summary["completed"],
                    "shed": summary["shed"],
                    "errors": summary["errors"],
                    "qps_achieved": summary["qps_achieved"],
                    "goodput_tokens": summary["goodput_tokens"],
                    "wall_s": summary["wall_s"],
                    "host_gap_ms_per_step": round(
                        1e3 * max(
                            (fleet_gap_steps()[0] if router is not None
                             else sched.host_gap_seconds) - gap0, 0.0)
                        / max((fleet_gap_steps()[1] if router is not None
                               else sched.decode_steps_total) - steps0,
                              1), 4),
                    "metrics": {
                        "histograms": hists,
                        "compile_counts": {
                            k: v for k, v in obs.compile_counts().items()
                            if v > 0},
                    },
                    "config": {
                        "model": model_name,
                        "backend": jax.default_backend(),
                        "num_slots": num_slots, "max_len": max_len,
                        "queue_limit": args.queue_limit,
                        "requests": requests, "tp": args.tp,
                        "page_size": engine.page_size,
                        "num_pages": engine.num_pages,
                        "prefill_chunk": engine.prefill_chunk,
                    },
                }
                if disagg:
                    line["handoff_bytes"] = \
                        sched.handoff_bytes_total - ho_bytes0
                    line["handoffs"] = sched.handoffs_total - ho_n0
                    line["config"]["prefill_slots"] = \
                        prefill_engine.num_slots
                    line["config"]["handoff_pages"] = \
                        engine.handoff_pages
                    line["config"]["prefill_device"] = \
                        str(prefill_dev) if prefill_dev else "shared"
                if router is not None:
                    line["dropped_streams"] = \
                        summary["dropped_streams"]
                    line["failovers"] = failovers
                    line["replicas_healthy"] = router.healthy_count()
                    line["config"]["kill_replica"] = args.kill_replica
                if args.kill_replica:
                    # the chaos line's hard gates: failover resumes
                    # streams (zero drops) and at least one failover
                    # actually happened (a vacuous kill must not pass)
                    if summary["dropped_streams"]:
                        raise SystemExit(
                            "bench_serve: %d accepted streams dropped "
                            "through the replica kill at qps=%s — "
                            "failover must resume streams, not drop "
                            "them" % (summary["dropped_streams"], qps))
                    if failovers < 1:
                        raise SystemExit(
                            "bench_serve: --kill-replica drive recorded "
                            "no router.failovers at qps=%s — the chaos "
                            "line was vacuous" % qps)
                if "wave" in summary:
                    line["wave"] = summary["wave"]
                    last_wave = summary["wave"]
                if summary["errors"]:
                    raise SystemExit(
                        "bench_serve: %d requests errored (not shed) at "
                        "qps=%s — a load line with silent failures must "
                        "not enter the trajectory" % (summary["errors"],
                                                      qps))
                if tracer is not None:
                    tracer.export_jsonl(args.trace_file)
                    counts = tracer.span_counts()
                    line["trace"] = {
                        "file": args.trace_file,
                        "spans": int(sum(counts.values())),
                        "requests": summary["completed"],
                    }
                print(json.dumps(line))
                sys.stdout.flush()
            info = {
                "wave": last_wave,
                "handoffs": getattr(sched, "handoffs_total", 0),
                "decode_route": getattr(sched,
                                        "decode_route_admissions", 0),
                "decode_chunks": getattr(sched,
                                         "decode_side_chunks", 0),
                "prefill_chunks": getattr(sched,
                                          "prefill_side_chunks", 0),
                "decode_compiles": engine.flight_state()
                                         ["compile_counts"],
                "prefill_compiles": (prefill_engine.flight_state()
                                     ["compile_counts"]
                                     if prefill_engine else None),
            }
        finally:
            fe.stop()
        return info

    arms = {"off": (False,), "on": (True,), "ab": (False, True)}
    results = {}
    for disagg in arms[args.disagg]:
        results[disagg] = run_arm(disagg)

    if args.ab_assert:
        def infl(w):
            if (not w or not w["wave_gaps"]
                    or not w["quiet_tpot_p99_ms"]):
                raise SystemExit("bench_serve: --ab-assert got no "
                                 "wave-window TPOT samples — raise "
                                 "--requests / --wave-repeats")
            if w["completed"] != w["requests"]:
                # a shed/errored wave offers no interference: a green
                # isolation verdict over it would be vacuous
                raise SystemExit(
                    "bench_serve: only %d of %d admission-wave requests "
                    "completed — raise --queue-limit or lower --wave"
                    % (w["completed"], w["requests"]))
            return w["wave_tpot_p99_ms"] / w["quiet_tpot_p99_ms"]
        colo, dis = (infl(results[False]["wave"]),
                     infl(results[True]["wave"]))
        print("# ab: colocated wave p99-TPOT inflation %.2fx, "
              "disagg %.2fx" % (colo, dis), file=sys.stderr)
        # structural isolation (every backend): the disagg arm handed
        # off, real prefill compute only ever ran on the prefill
        # engine (every decode-side chunk was a single-chunk
        # full-prefix-hit admission — no transfer, no recompute, by
        # construction 1 token), and the handoff pair compiled exactly
        # once per role
        d = results[True]
        if not d["handoffs"]:
            raise SystemExit("bench_serve: the disagg arm completed no "
                             "handoffs — the A/B never exercised the "
                             "role split")
        if not d["prefill_chunks"]:
            raise SystemExit("bench_serve: the disagg arm ran no "
                             "prefill-engine chunks")
        if d["decode_chunks"] != d["decode_route"]:
            raise SystemExit(
                "bench_serve: the disagg DECODE engine ran %d chunks "
                "for %d full-hit admissions — prefill compute leaked "
                "into the decode role"
                % (d["decode_chunks"], d["decode_route"]))
        dc, pc = d["decode_compiles"], d["prefill_compiles"]
        if dc.get("kv_import") != 1 or pc.get("kv_export") != 1:
            raise SystemExit(
                "bench_serve: handoff programs not compiled exactly "
                "once (decode %r / prefill %r)" % (dc, pc))
        # wall-clock isolation: an ON-CHIP claim (separate chips).  CPU
        # hosts share cores across the virtual devices — same
        # discipline as the trajectory gate: CPU numbers are reported,
        # never perf-gated.
        if on_tpu:
            if colo <= 1.05:
                raise SystemExit(
                    "bench_serve: the admission wave did not measurably "
                    "degrade the colocated baseline (%.2fx <= 1.05x) — "
                    "the A/B is not exercising interference; raise "
                    "--wave or prompt lengths" % colo)
            if dis >= colo:
                raise SystemExit(
                    "bench_serve: disagg in-flight p99 TPOT inflation "
                    "%.2fx is not below the colocated baseline's %.2fx "
                    "— decode-TPOT isolation regressed" % (dis, colo))


if __name__ == "__main__":
    main()
