"""Benchmark: goodput-vs-QPS through the LIVE async serving front-end.

The load harness half of ISSUE 13: starts the real HTTP front-end
(`paddle_tpu.serving.frontend`) over the compiled engine, offers Poisson
traffic at each requested QPS over a prompt/output length mix
(`paddle_tpu.serving.loadgen`), and prints ONE JSON line per (QPS, mix)
— the ``BENCH_serve_*`` trajectory format::

  {"metric": "serve_goodput_tokens_per_sec", "value": N, "unit": "tok/s",
   "qps": ..., "mix": ..., "ttft_p50_ms": ..., "ttft_p99_ms": ...,
   "tpot_p50_ms": ..., "tpot_p99_ms": ..., "shed_rate": ...,
   "cache_layout": ..., "kv_dtype": ..., "spec": ..., "tp": ...,
   "overlap": ..., "metrics": {...}, "config": {...}}

Every field the decode trajectory cursors key on rides along, plus the
serve axes (qps, mix, overlap): ``tools/bench_schema.py --trajectory``
gates serve lines like-for-like — >3% goodput drop OR >3% p99-TTFT
growth between consecutive on-chip entries fails; CPU lines are smoke
and never perf-gate.  TTFT/TPOT here are measured at the CLIENT (first
delivered SSE token), so queueing, HTTP framing, and the scheduler
thread handoff are all inside the number — the p99 is what a user
would see, not what the engine dispatched.

The engine runs the OVERLAPPED decode loop (``--overlap off`` for the
sync A/B) under the STRICT recompile watchdog: the decode program must
compile exactly once across the whole sweep — admission churn, shed
bursts, mid-stream disconnects and all (the schema gate re-checks the
reported count).

On TPU: GPT-2 345M at serving shapes.  On CPU: the tiny head_dim-64
smoke config (numbers are smoke; the line carries backend so the gate
knows).  Knobs: PADDLE_TPU_BENCH_SLOTS / _REQUESTS.
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None):
    os.environ.setdefault("PADDLE_TPU_STRICT_COMPILE", "1")
    ap = argparse.ArgumentParser(
        prog="python bench_serve.py",
        description="serving front-end load benchmark (goodput vs QPS)")
    ap.add_argument("--qps", default="4,16",
                    help="comma list of offered Poisson rates (one "
                         "BENCH_serve line each)")
    ap.add_argument("--mix", default="short",
                    help="prompt/output length mix name (serving."
                         "loadgen.MIXES: short|mixed|long)")
    ap.add_argument("--requests", type=int, default=None,
                    help="requests per QPS point (default 12 CPU / 32 "
                         "TPU; PADDLE_TPU_BENCH_REQUESTS overrides)")
    ap.add_argument("--queue-limit", type=int, default=32,
                    help="front-end admission bound (shed with 429 "
                         "above it)")
    ap.add_argument("--overlap", default="on", choices=("on", "off"),
                    help="overlapped host/device decode loop (off = the "
                         "sync A/B baseline)")
    ap.add_argument("--kv-dtype", default="bf16", choices=("bf16", "int8"))
    ap.add_argument("--spec", default="off",
                    help="'off' or a speculative draft length k")
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel degree (needs tp devices)")
    ap.add_argument("--trace-file", default=None, metavar="PATH",
                    help="export the request-scoped span trace (JSONL) "
                         "of the LAST QPS point's drive")
    args = ap.parse_args(argv)

    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import observability as obs
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.observability import flight as _flight
    from paddle_tpu.observability import tracing as _tracing
    from paddle_tpu.observability import watchdog as _wd
    from paddle_tpu.serving import loadgen
    from paddle_tpu.serving.engine import DecodeEngine
    from paddle_tpu.serving.frontend import ServingFrontend

    spec = 0 if args.spec in ("off", "0") else int(args.spec)
    overlap = args.overlap == "on"
    on_tpu = jax.default_backend() == "tpu"
    if args.tp > len(jax.devices()):
        raise SystemExit(
            "bench_serve: --tp %d needs %d devices, have %d (CPU: set "
            "XLA_FLAGS=--xla_force_host_platform_device_count)"
            % (args.tp, args.tp, len(jax.devices())))
    paddle.seed(0)
    if on_tpu:
        cfg = GPTConfig.gpt2_medium()
        model_name = "gpt2_345m"
        num_slots, requests, max_len, page_size = 8, 32, 1024, 64
    else:
        cfg = GPTConfig(vocab_size=512, max_position_embeddings=256,
                        hidden_size=128, num_hidden_layers=2,
                        num_attention_heads=2, intermediate_size=256)
        model_name = "tiny_d64"
        num_slots, requests, max_len, page_size = 4, 12, 128, 16
    num_slots = int(os.getenv("PADDLE_TPU_BENCH_SLOTS", num_slots))
    requests = int(args.requests if args.requests is not None
                   else os.getenv("PADDLE_TPU_BENCH_REQUESTS", requests))
    cfg.hidden_dropout_prob = cfg.attention_dropout_prob = 0.0
    model = GPTForCausalLM(cfg)
    if on_tpu:
        paddle.amp.decorate(model, level="O2", dtype="bfloat16")
    model.eval()

    qps_list = [float(t) for t in str(args.qps).split(",") if t.strip()]
    tracer = _tracing.Tracer() if args.trace_file else None
    engine = DecodeEngine(model, num_slots=num_slots, max_len=max_len,
                          seed=0, page_size=page_size,
                          kv_dtype=("int8" if args.kv_dtype == "int8"
                                    else None),
                          spec_k=spec, tracer=tracer, tp=args.tp)
    fe = ServingFrontend(engine, queue_limit=args.queue_limit,
                         overlap=overlap, tracer=tracer)
    host, port = fe.start()
    try:
        # warmup drive: compiles prefill + the decode-side step once
        loadgen.run_load_sync(host, port, qps=max(qps_list), n_requests=2,
                              mix=args.mix, seed=99,
                              vocab=cfg.vocab_size)
        for qps in qps_list:
            # percentiles must describe THIS point's drive (reset
            # ordering per OBSERVABILITY.md: flight snapshot first,
            # then registry reset, then watchdog shadow resync)
            _flight.note_registry_reset()
            obs.default_registry().reset()
            _wd.resync_counter()
            if tracer is not None:
                tracer.reset()
            # host-gap delta for THIS point only (one scheduler serves
            # the whole sweep; idle arrival gaps are already excluded
            # by the scheduler's pipeline-idle reset)
            gap0 = fe.scheduler.host_gap_seconds
            steps0 = fe.scheduler.decode_steps_total
            summary = loadgen.run_load_sync(
                host, port, qps=qps, n_requests=requests, mix=args.mix,
                seed=0, vocab=cfg.vocab_size)

            def _pcts(name):
                h = obs.histogram(name)
                return {"p50_ms": round(1e3 * h.percentile(0.50), 3),
                        "p95_ms": round(1e3 * h.percentile(0.95), 3),
                        "p99_ms": round(1e3 * h.percentile(0.99), 3),
                        "count": h.count}

            sched = fe.scheduler
            line = {
                "metric": "serve_goodput_tokens_per_sec",
                "value": summary["goodput_tokens_per_sec"],
                "unit": "tok/s",
                # the serve trajectory cursor axes (bench_schema keys
                # series on model+layout+kv+spec+tp+overlap+qps+mix)
                "qps": summary["qps"],
                "mix": summary["mix"],
                "cache_layout": "paged",
                "kv_dtype": args.kv_dtype,
                "spec": spec,
                "tp": args.tp,
                "overlap": overlap,
                # client-observed latency (the acceptance numbers)
                "ttft_p50_ms": summary["ttft_p50_ms"],
                "ttft_p99_ms": summary["ttft_p99_ms"],
                "tpot_p50_ms": summary["tpot_p50_ms"],
                "tpot_p99_ms": summary["tpot_p99_ms"],
                "shed_rate": summary["shed_rate"],
                "sent": summary["sent"],
                "completed": summary["completed"],
                "shed": summary["shed"],
                "errors": summary["errors"],
                "qps_achieved": summary["qps_achieved"],
                "goodput_tokens": summary["goodput_tokens"],
                "wall_s": summary["wall_s"],
                "host_gap_ms_per_step": round(
                    1e3 * (sched.host_gap_seconds - gap0)
                    / max(sched.decode_steps_total - steps0, 1), 4),
                "metrics": {
                    "histograms": {
                        "serving.ttft_seconds":
                            _pcts("serving.ttft_seconds"),
                        "serving.tpot_seconds":
                            _pcts("serving.tpot_seconds"),
                        "serving.queue_wait_seconds":
                            _pcts("serving.queue_wait_seconds"),
                        "serving.decode_step_seconds":
                            _pcts("serving.decode_step_seconds"),
                    },
                    "compile_counts": {
                        k: v for k, v in obs.compile_counts().items()
                        if v > 0},
                },
                "config": {
                    "model": model_name,
                    "backend": jax.default_backend(),
                    "num_slots": num_slots, "max_len": max_len,
                    "queue_limit": args.queue_limit,
                    "requests": requests, "tp": args.tp,
                    "page_size": engine.page_size,
                    "num_pages": engine.num_pages,
                    "prefill_chunk": engine.prefill_chunk,
                },
            }
            if summary["errors"]:
                raise SystemExit(
                    "bench_serve: %d requests errored (not shed) at "
                    "qps=%s — a load line with silent failures must "
                    "not enter the trajectory" % (summary["errors"],
                                                  qps))
            if tracer is not None:
                tracer.export_jsonl(args.trace_file)
                counts = tracer.span_counts()
                line["trace"] = {
                    "file": args.trace_file,
                    "spans": int(sum(counts.values())),
                    "requests": summary["completed"],
                }
            print(json.dumps(line))
            sys.stdout.flush()
    finally:
        fe.stop()


if __name__ == "__main__":
    main()
